"""
The telemetry runtime (observability/telemetry.py): span semantics, the
dependency-light metrics registry, both exporters, and the end-to-end
``batch-build --trace-file/--metrics-file`` contract under fault injection.
"""

import json
import sys
import threading

import pytest

from gordo_tpu.observability import metrics as metric_catalog
from gordo_tpu.observability import telemetry
from gordo_tpu.util import faults, profiling


@pytest.fixture(autouse=True)
def _fresh_telemetry(monkeypatch):
    """Every test starts with spans disabled, no trace, zeroed values, and
    no leaked fault plan or profile dir."""
    monkeypatch.delenv("GORDO_TPU_PROFILE_DIR", raising=False)
    monkeypatch.delenv(faults.PLAN_ENV, raising=False)
    faults.reset_plan()
    telemetry.reset()
    yield
    faults.reset_plan()
    telemetry.reset()


# ---------------------------------------------------------------- registry
def test_counter_gauge_histogram_roundtrip():
    reg = telemetry.MetricsRegistry()
    c = reg.counter("gordo_t_events_total", "events", ("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2.5)
    c.labels(kind="b").inc()
    assert c.value(kind="a") == 3.5
    g = reg.gauge("gordo_t_level", "level")
    g.set(7)
    assert g.value() == 7.0
    h = reg.histogram(
        "gordo_t_dur_seconds", "durations", buckets=(0.1, 1.0)
    )
    h.observe(0.05)
    h.observe(0.5)
    h.observe(100.0)  # lands in the implicit +Inf bucket
    assert h.count() == 3


def test_registry_get_or_create_and_kind_mismatch():
    reg = telemetry.MetricsRegistry()
    c1 = reg.counter("gordo_t_x_total", "x", ("a",))
    c2 = reg.counter("gordo_t_x_total", "x again", ("a",))
    assert c1 is c2  # module re-imports converge on one series
    with pytest.raises(ValueError):
        reg.gauge("gordo_t_x_total", "not a counter", ("a",))
    with pytest.raises(ValueError):
        reg.counter("gordo_t_x_total", "other labels", ("b",))
    with pytest.raises(ValueError):
        reg.counter("gordo_t_y_total", "")  # empty help rejected at runtime
    with pytest.raises(ValueError):
        reg.counter("bad name!", "help")


def test_textfile_exposition_format(tmp_path):
    """The pure-python renderer must be valid Prometheus text format 0.0.4
    — this is the no-prometheus_client code path (it imports nothing)."""
    reg = telemetry.MetricsRegistry()
    c = reg.counter("gordo_t_total", "with \"quotes\" and\nnewline", ("m",))
    c.labels(m='va"l').inc(2)
    h = reg.histogram("gordo_t_s", "hist help", buckets=(0.5, 1.0))
    h.observe(0.25)
    h.observe(0.75)
    text = reg.render_text()
    assert '# HELP gordo_t_total with "quotes" and\\nnewline' in text
    assert "# TYPE gordo_t_total counter" in text
    assert 'gordo_t_total{m="va\\"l"} 2.0' in text
    # histogram: cumulative buckets, +Inf, _sum and _count
    assert 'gordo_t_s_bucket{le="0.5"} 1' in text
    assert 'gordo_t_s_bucket{le="1.0"} 2' in text
    assert 'gordo_t_s_bucket{le="+Inf"} 2' in text
    assert "gordo_t_s_count 2" in text
    assert "gordo_t_s_sum 1.0" in text
    out = tmp_path / "metrics" / "out.prom"
    reg.write_textfile(str(out))
    assert out.read_text() == text


def test_prometheus_bridge_exposes_registry_values():
    prometheus_client = pytest.importorskip("prometheus_client")
    reg = telemetry.MetricsRegistry()
    reg.counter("gordo_t_br_total", "bridged", ("k",)).labels(k="x").inc(4)
    h = reg.histogram("gordo_t_br_s", "bridged hist", buckets=(1.0,))
    h.observe(0.5)
    prom = prometheus_client.CollectorRegistry()
    assert telemetry.prometheus_bridge(prom, reg) is not None
    out = prometheus_client.generate_latest(prom).decode()
    assert 'gordo_t_br_total{k="x"} 4.0' in out
    assert 'gordo_t_br_s_bucket{le="1.0"} 1.0' in out
    assert "gordo_t_br_s_sum 0.5" in out


def test_prometheus_bridge_multiprocess_mode(tmp_path, monkeypatch):
    """The bridge coexists with the MultiProcessCollector: in multiprocess
    serving mode /metrics still carries the worker's telemetry series."""
    prometheus_client = pytest.importorskip("prometheus_client")
    monkeypatch.setenv("PROMETHEUS_MULTIPROC_DIR", str(tmp_path))
    from gordo_tpu.server.prometheus.metrics import create_registry

    prom = create_registry()
    reg = telemetry.MetricsRegistry()
    reg.counter("gordo_t_mp_total", "multiproc bridged").inc()
    telemetry.prometheus_bridge(prom, reg)
    out = prometheus_client.generate_latest(prom).decode()
    assert "gordo_t_mp_total 1.0" in out


def test_prometheus_bridge_without_prometheus_client(monkeypatch):
    """Absent prometheus_client the bridge declines (None) and the textfile
    path still works — batch jobs export without the dependency."""
    monkeypatch.setitem(sys.modules, "prometheus_client", None)
    monkeypatch.setitem(sys.modules, "prometheus_client.core", None)
    reg = telemetry.MetricsRegistry()
    reg.counter("gordo_t_nopc_total", "no client").inc()

    class _Sink:
        def register(self, collector):  # pragma: no cover - must not run
            raise AssertionError("bridge must not register without client")

    assert telemetry.prometheus_bridge(_Sink(), reg) is None
    assert "gordo_t_nopc_total 1.0" in reg.render_text()


# ------------------------------------------------------------------- spans
def test_disabled_span_is_shared_noop_singleton():
    """The acceptance guard: with no trace, no span timing, and no profile
    dir, span() allocates nothing — every call returns the same no-op
    object and records no events or metrics."""
    s1 = telemetry.span("compile", machine="m-1")
    s2 = telemetry.span("train")
    assert s1 is s2
    with s1:
        pass
    assert telemetry.chrome_trace() is None
    assert not telemetry.spans_enabled()
    # and nothing observed into the phase histogram
    assert metric_catalog.BUILD_PHASE_SECONDS.count(phase="compile") == 0


def test_span_records_chrome_trace_event_and_histogram():
    telemetry.start_trace()
    hist = metric_catalog.BUILD_PHASE_SECONDS.labels(phase="compile")
    with telemetry.span("compile", hist, bucket="b0", machines=3):
        pass
    trace = telemetry.chrome_trace()
    [event] = trace["traceEvents"]
    assert event["name"] == "compile"
    assert event["ph"] == "X"
    assert event["ts"] >= 0 and event["dur"] >= 0
    assert event["args"] == {"bucket": "b0", "machines": "3"}
    assert isinstance(event["pid"], int) and isinstance(event["tid"], int)
    json.dumps(trace)  # schema must be JSON-serializable as-is
    assert metric_catalog.BUILD_PHASE_SECONDS.count(phase="compile") == 1


def test_enable_spans_times_without_recording_events():
    """--metrics-file without --trace-file: histograms fill, no event
    buffer grows."""
    telemetry.enable_spans()
    hist = metric_catalog.BUILD_PHASE_SECONDS.labels(phase="fetch")
    with telemetry.span("fetch", hist, machine="m"):
        pass
    assert telemetry.chrome_trace() is None
    assert metric_catalog.BUILD_PHASE_SECONDS.count(phase="fetch") == 1


def test_spans_thread_safe_under_concurrency():
    telemetry.start_trace()
    n_threads, n_spans = 8, 50
    counter = telemetry.default_registry().counter(
        "gordo_t_thread_total", "thread-safety probe"
    )
    # all threads in-flight together: thread idents are only guaranteed
    # distinct among live threads
    barrier = threading.Barrier(n_threads)

    def work(tid):
        barrier.wait()
        for i in range(n_spans):
            with telemetry.span("work", thread=tid, i=i):
                counter.inc()

    threads = [
        threading.Thread(target=work, args=(t,)) for t in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    trace = telemetry.stop_trace()
    assert len(trace["traceEvents"]) == n_threads * n_spans
    assert counter.value() == n_threads * n_spans
    # distinct tids recorded per thread
    assert len({e["tid"] for e in trace["traceEvents"]}) == n_threads


def test_trace_buffer_bounded(monkeypatch):
    monkeypatch.setattr(telemetry._TraceBuffer, "MAX_EVENTS", 3)
    telemetry.start_trace()
    for i in range(5):
        with telemetry.span("e", i=i):
            pass
    trace = telemetry.stop_trace()
    assert len(trace["traceEvents"]) == 3
    assert trace["otherData"]["droppedEvents"] == 2


def test_write_trace_roundtrip(tmp_path):
    telemetry.start_trace()
    with telemetry.span("fetch", machine="m-0"):
        pass
    path = tmp_path / "trace" / "out.json"
    telemetry.write_trace(str(path))
    data = json.loads(path.read_text())
    assert data["traceEvents"][0]["name"] == "fetch"
    assert data["displayTimeUnit"] == "ms"


def test_write_trace_without_trace_raises(tmp_path):
    with pytest.raises(RuntimeError):
        telemetry.write_trace(str(tmp_path / "out.json"))


# ------------------------------------------------- profiling integration
def test_annotate_is_nullcontext_unless_profiling(monkeypatch):
    import contextlib

    monkeypatch.delenv(profiling.PROFILE_DIR_ENV, raising=False)
    assert isinstance(profiling.annotate("x"), contextlib.nullcontext)
    assert not profiling.profiling_enabled()


def test_profile_dir_activates_spans(monkeypatch, tmp_path):
    """With GORDO_TPU_PROFILE_DIR set, spans leave the no-op path so their
    names reach the JAX device trace via annotate()."""
    monkeypatch.setenv(profiling.PROFILE_DIR_ENV, str(tmp_path))
    s = telemetry.span("compile")
    assert s is not telemetry._NULL_SPAN
    with s:  # enters a real jax TraceAnnotation without an active trace
        pass


# --------------------------------------------------------- end-to-end CLI
def _machine_block(name):
    tags = "".join(f"\n      - {name}-tag-{j}" for j in range(4))
    return f"""
  - name: {name}
    dataset:
      tags:{tags}
      train_start_date: '2019-01-01T00:00:00+00:00'
      train_end_date: '2019-01-03T00:00:00+00:00'
      data_provider: {{type: RandomDataProvider}}
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        require_thresholds: true
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
            - sklearn.preprocessing.MinMaxScaler
            - gordo_tpu.models.models.AutoEncoder:
                kind: feedforward_hourglass
                epochs: 1
"""


def test_batch_build_trace_and_metrics_files(tmp_path, monkeypatch):
    """The acceptance contract: a faulted 3-machine batch-build with
    --trace-file/--metrics-file produces (1) valid Chrome-trace JSON with
    fetch/compile/train/serialize spans for each machine/bucket and (2) a
    Prometheus textfile including the fault-domain counters."""
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import gordo

    monkeypatch.setenv("GORDO_TPU_FAULT_BACKOFF_BASE", "0")
    monkeypatch.setenv(
        faults.PLAN_ENV,
        json.dumps(
            {
                "rules": [
                    {"site": "data_fetch", "machine": "tl-1", "times": -1,
                     "error": "permanent"}
                ]
            }
        ),
    )
    faults.reset_plan()
    config_file = tmp_path / "config.yaml"
    config_file.write_text(
        "machines:" + "".join(_machine_block(f"tl-{i}") for i in range(3))
    )
    trace_file = tmp_path / "out.json"
    metrics_file = tmp_path / "out.prom"
    result = CliRunner().invoke(
        gordo,
        [
            "batch-build", str(config_file),
            "--output-dir", str(tmp_path / "models"),
            "--trace-file", str(trace_file),
            "--metrics-file", str(metrics_file),
        ],
    )
    assert result.exit_code == faults.EXIT_PARTIAL, result.output

    # (1) the Chrome trace: parseable, and phase spans per machine/bucket
    trace = json.loads(trace_file.read_text())
    events = trace["traceEvents"]
    assert events and all(e["ph"] == "X" for e in events)
    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    fetch_machines = {
        e["args"]["machine"] for e in by_name["fetch"]
    }
    assert {"tl-0", "tl-1", "tl-2"} <= fetch_machines
    serialize_machines = {
        e["args"]["machine"] for e in by_name["serialize"]
    }
    assert serialize_machines == {"tl-0", "tl-2"}  # tl-1 quarantined
    assert by_name["compile"] and by_name["train"]
    assert all("bucket" in e["args"] for e in by_name["compile"])

    # (2) the Prometheus textfile: fault-domain counters present
    prom = metrics_file.read_text()
    assert 'gordo_build_quarantines_total{stage="data_fetch"} 1.0' in prom
    assert 'gordo_build_machines_total{outcome="quarantined"} 1.0' in prom
    assert 'gordo_build_machines_total{outcome="built"} 2.0' in prom
    assert "gordo_build_phase_seconds_bucket" in prom
    assert 'gordo_build_program_cache_requests_total' in prom
