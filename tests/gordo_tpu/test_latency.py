"""
The log-bucketed latency histogram (observability/latency.py): the load
harness's percentile math must itself be trustworthy — merge associativity,
quantile accuracy against a sorted-array reference within the documented
error bound, thread-safety, serialization, and the coordinated-omission
correction (a stalled server must inflate p99, never hide it).
"""

import json
import random
import threading

import pytest

from gordo_tpu.observability.latency import (
    DEFAULT_SUBBUCKETS,
    LatencyHistogram,
)


def _reference_quantile(values, q):
    """Nearest-rank quantile over the retained samples."""
    import math

    ordered = sorted(values)
    if q <= 0:
        return ordered[0]
    if q >= 1:
        return ordered[-1]
    rank = max(1, math.ceil(q * len(ordered)))
    return ordered[rank - 1]


@pytest.mark.parametrize("distribution", ["uniform", "lognormal", "bimodal"])
def test_quantiles_match_sorted_reference_within_error_bound(distribution):
    rng = random.Random(7)
    if distribution == "uniform":
        values = [rng.uniform(1e-4, 2.0) for _ in range(20_000)]
    elif distribution == "lognormal":
        values = [rng.lognormvariate(-5.0, 1.5) for _ in range(20_000)]
    else:
        values = [
            rng.uniform(0.001, 0.002) if rng.random() < 0.99
            else rng.uniform(1.0, 2.0)
            for _ in range(20_000)
        ]
    hist = LatencyHistogram()
    for value in values:
        hist.record(value)
    assert hist.count == len(values)
    for q in (0.5, 0.9, 0.99, 0.999):
        got = hist.quantile(q)
        want = _reference_quantile(values, q)
        # bucket midpoints are exact to rel_error_bound; rank-vs-bucket
        # boundary effects at repeated values allow one extra bucket width
        tolerance = want * (4.0 / DEFAULT_SUBBUCKETS)
        assert abs(got - want) <= tolerance, (q, got, want)


def test_exact_min_max_and_edge_quantiles():
    hist = LatencyHistogram()
    for value in (0.010, 0.020, 0.500):
        hist.record(value)
    assert hist.quantile(0.0) == pytest.approx(0.010)
    assert hist.quantile(1.0) == pytest.approx(0.500)
    summary = hist.summary()
    assert summary["count"] == 3
    assert summary["min_s"] == pytest.approx(0.010)
    assert summary["max_s"] == pytest.approx(0.500)
    assert summary["mean_s"] == pytest.approx((0.01 + 0.02 + 0.5) / 3)
    assert set(summary) >= {"p50_s", "p90_s", "p99_s", "p99.9_s"}


def test_empty_histogram_reports_none():
    hist = LatencyHistogram()
    assert hist.quantile(0.5) is None
    assert hist.summary()["p99_s"] is None
    assert hist.summary()["count"] == 0


def test_bad_values_clamped_not_raised():
    hist = LatencyHistogram()
    hist.record(0.0)
    hist.record(-5.0)
    hist.record(float("nan"))
    hist.record(float("inf"))
    assert hist.count == 4
    assert hist.quantile(1.0) <= 1e9


def test_merge_associative_and_commutative():
    rng = random.Random(3)
    shards = [
        [rng.lognormvariate(-4.0, 1.0) for _ in range(2_000)]
        for _ in range(3)
    ]

    def hist_of(values):
        h = LatencyHistogram()
        for v in values:
            h.record(v)
        return h

    a, b, c = (hist_of(s) for s in shards)
    left = LatencyHistogram().merge(a).merge(b).merge(c)
    bc = LatencyHistogram().merge(b).merge(c)
    right = LatencyHistogram().merge(a).merge(bc)
    reversed_order = LatencyHistogram.merged([c, b, a])
    flat = hist_of([v for s in shards for v in s])
    for q in (0.5, 0.9, 0.99, 0.999):
        assert left.quantile(q) == right.quantile(q) == \
            reversed_order.quantile(q) == flat.quantile(q)
    assert left.count == right.count == flat.count == 6_000
    assert left.to_dict()["buckets"] == flat.to_dict()["buckets"]


def test_merge_rejects_mismatched_subbuckets():
    with pytest.raises(ValueError):
        LatencyHistogram(64).merge(LatencyHistogram(32))


def test_thread_safety_shared_instance():
    """8 writers into ONE shared histogram: no lost updates."""
    hist = LatencyHistogram()
    per_thread = 5_000
    rng_seed = [11, 22, 33, 44, 55, 66, 77, 88]

    def write(seed):
        rng = random.Random(seed)
        for _ in range(per_thread):
            hist.record(rng.uniform(0.001, 0.1))

    threads = [threading.Thread(target=write, args=(s,)) for s in rng_seed]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert hist.count == per_thread * len(threads)
    assert sum(hist.to_dict()["buckets"].values()) == hist.count


def test_per_thread_histograms_merge_equals_shared():
    """The recommended hot-path pattern: per-worker histograms merged
    afterwards must agree with a single shared histogram."""
    values = [random.Random(9).uniform(1e-3, 1.0) for _ in range(9_000)]
    shared = LatencyHistogram()
    workers = [LatencyHistogram() for _ in range(3)]
    for i, value in enumerate(values):
        shared.record(value)
        workers[i % 3].record(value)
    merged = LatencyHistogram.merged(workers)
    assert merged.to_dict()["buckets"] == shared.to_dict()["buckets"]
    assert merged.quantile(0.999) == shared.quantile(0.999)


def test_serialization_roundtrip_through_json():
    hist = LatencyHistogram()
    rng = random.Random(5)
    for _ in range(1_000):
        hist.record(rng.lognormvariate(-3.0, 1.0))
    payload = json.loads(json.dumps(hist.to_dict()))
    restored = LatencyHistogram.from_dict(payload)
    assert restored.count == hist.count
    for q in (0.5, 0.99, 0.999):
        assert restored.quantile(q) == hist.quantile(q)
    # a restored histogram keeps merging (the bench parent's use case)
    restored.merge(hist)
    assert restored.count == 2 * hist.count


def test_coordinated_omission_correction_inflates_p99():
    """Closed-loop accounting: 1000 requests at 1ms, then ONE 2-second
    stall. Uncorrected, the stall is a single outlier and p99 stays ~1ms —
    the lie coordinated omission tells. With the expected-interval
    correction the back-filled samples surface the stall in p99."""
    interval = 0.001
    uncorrected = LatencyHistogram()
    corrected = LatencyHistogram()
    for _ in range(100_000):
        uncorrected.record(interval)
        corrected.record_with_expected_interval(interval, interval)
    uncorrected.record(2.0)
    corrected.record_with_expected_interval(2.0, interval)

    assert uncorrected.quantile(0.99) < 0.002  # the stall is hidden
    # corrected: ~2000 back-filled samples spanning (0, 2s] join 100k good
    # ones — p99 must now report ~1s of queueing, while p50 stays ~1ms
    assert corrected.quantile(0.99) > 0.5
    assert corrected.quantile(0.5) < 0.01


def test_expected_interval_noop_without_interval():
    hist = LatencyHistogram()
    hist.record_with_expected_interval(1.0, None)
    hist.record_with_expected_interval(1.0, 0.0)
    assert hist.count == 2
