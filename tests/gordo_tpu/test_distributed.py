"""
Multi-host batch training: 2 jax processes, one global mesh, sharded fleet.

The SPMD replacement for the reference's one-pod-per-machine Argo fan-out
(argo-workflow.yml.template:1511-1525): both processes run the same
batch-build; the machines axis spans all 8 devices (4 per process); each
process assembles and saves only its local shard. The test asserts the two
shards partition the fleet exactly and that a distributed-trained model is
numerically identical to the same machine trained single-process.
"""

import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_MACHINES = 8

CONFIG = {
    "machines": [
        {
            "name": f"dist-m{i}",
            "dataset": {
                "type": "RandomDataset",
                "train_start_date": "2019-01-01T00:00:00+00:00",
                "train_end_date": "2019-01-02T00:00:00+00:00",
                "tags": [f"dtag-{i}-a", f"dtag-{i}-b"],
            },
            "model": {
                "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
                    "base_estimator": {
                        "gordo_tpu.models.models.AutoEncoder": {
                            "kind": "feedforward_hourglass",
                            "epochs": 1,
                        }
                    }
                }
            },
        }
        for i in range(N_MACHINES)
    ]
    + [
        # seeded-KFold KFCV machine: exercises the permuted bucket program
        # (replicated perms array) on the multi-host mesh
        {
            "name": "dist-kfold",
            "dataset": {
                "type": "RandomDataset",
                "train_start_date": "2019-01-01T00:00:00+00:00",
                "train_end_date": "2019-01-02T00:00:00+00:00",
                "tags": ["dtag-kf-a", "dtag-kf-b"],
            },
            "model": {
                "gordo_tpu.models.anomaly.diff.DiffBasedKFCVAnomalyDetector": {
                    "window": 12,
                    "base_estimator": {
                        "gordo_tpu.models.models.AutoEncoder": {
                            "kind": "feedforward_hourglass",
                            "epochs": 1,
                        }
                    },
                }
            },
            "evaluation": {
                "cv": {
                    "sklearn.model_selection.KFold": {
                        "n_splits": 3, "shuffle": True, "random_state": 0,
                    }
                }
            },
        }
    ]
}

WORKER = """
import json, os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

from gordo_tpu import serializer
from gordo_tpu.parallel import BatchedModelBuilder, distributed
from gordo_tpu.workflow.normalized_config import NormalizedConfig
import yaml

pid = int(sys.argv[1])
outdir = sys.argv[2]
coordinator = sys.argv[3]

multi = distributed.initialize(coordinator, num_processes=2, process_id=pid)
assert multi, "expected a multi-process world"
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

with open(os.path.join(outdir, "config.yaml")) as f:
    config = yaml.safe_load(f)
norm = NormalizedConfig(config, project_name="dist-test")
results = BatchedModelBuilder(norm.machines).build()

names = []
for model, machine_out in results:
    mdir = os.path.join(outdir, machine_out.name)
    os.makedirs(mdir, exist_ok=True)
    serializer.dump(model, mdir, metadata=machine_out.to_dict())
    names.append(machine_out.name)
with open(os.path.join(outdir, "manifest-{{}}.json".format(pid)), "w") as f:
    json.dump(names, f)
print("worker", pid, "built", names, flush=True)
"""


from _nethelpers import free_port as _free_port  # noqa: E402


@pytest.fixture(scope="module")
def dist_outdir():
    outdir = tempfile.mkdtemp(prefix="gordo-dist-")
    with open(os.path.join(outdir, "config.yaml"), "w") as f:
        yaml.safe_dump(CONFIG, f)
    worker_py = os.path.join(outdir, "worker.py")
    with open(worker_py, "w") as f:
        f.write(WORKER.format(repo=REPO))
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {
        k: v for k, v in os.environ.items() if not k.startswith("XLA_FLAGS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, worker_py, str(pid), outdir, coordinator],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outputs.append(out)
    for p, out in zip(procs, outputs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
    return outdir


def test_processes_partition_the_fleet(dist_outdir):
    manifests = []
    for pid in range(2):
        with open(os.path.join(dist_outdir, f"manifest-{pid}.json")) as f:
            manifests.append(json.load(f))
    all_names = {f"dist-m{i}" for i in range(N_MACHINES)} | {"dist-kfold"}
    built = [name for m in manifests for name in m]
    assert sorted(built) == sorted(all_names), (manifests, all_names)
    # disjoint shards: no machine trained (or saved) twice
    assert len(built) == len(set(built))
    # both hosts did real work
    assert all(len(m) > 0 for m in manifests)


def test_artifacts_load_and_score(dist_outdir):
    import pandas as pd

    from gordo_tpu import serializer

    name = "dist-m3"
    model = serializer.load(os.path.join(dist_outdir, name))
    cols = [f"dtag-3-a", f"dtag-3-b"]
    idx = pd.date_range("2019-02-01", periods=30, freq="10min", tz="UTC")
    X = pd.DataFrame(
        np.random.RandomState(0).rand(30, 2), index=idx, columns=cols
    )
    frame = model.anomaly(X, X.copy(), frequency=pd.Timedelta("10min"))
    total = frame["total-anomaly-scaled"].to_numpy()
    assert np.isfinite(total).all()


def test_kfold_kfcv_trained_on_multihost_mesh(dist_outdir):
    """The seeded-KFold permuted program ran distributed and produced a
    working thresholded detector."""
    from gordo_tpu import serializer
    from gordo_tpu.models.anomaly.diff import DiffBasedKFCVAnomalyDetector

    model = serializer.load(os.path.join(dist_outdir, "dist-kfold"))
    assert isinstance(model, DiffBasedKFCVAnomalyDetector)
    assert np.isfinite(model.aggregate_threshold_)
    assert np.isfinite(np.asarray(model.feature_thresholds_)).all()


def test_distributed_matches_single_process(dist_outdir):
    """A machine trained on the 2-process world must equal the same machine
    trained in this (single-process, 8-virtual-device) process: per-machine
    math is device-local either way."""
    from gordo_tpu import serializer
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel import BatchedModelBuilder

    machines = [
        Machine.from_config(c, project_name="dist-test")
        for c in CONFIG["machines"]
    ]
    results = BatchedModelBuilder(machines).build()
    by_name = {m.name: model for model, m in results}

    def inner_params(model):
        est = model.base_estimator
        if hasattr(est, "steps"):
            est = est.steps[-1][1]
        return est.params_

    for name in ("dist-m0", "dist-m7"):
        dist_model = serializer.load(os.path.join(dist_outdir, name))
        local_model = by_name[name]
        dist_params = inner_params(dist_model)
        local_params = inner_params(local_model)
        flat_d, _ = __import__("jax").tree_util.tree_flatten(dist_params)
        flat_l, _ = __import__("jax").tree_util.tree_flatten(local_params)
        assert len(flat_d) == len(flat_l)
        for a, b in zip(flat_d, flat_l):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )


WORKER_RESUME = """
import json, os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

from gordo_tpu.parallel import BatchedModelBuilder, distributed
from gordo_tpu.workflow.normalized_config import NormalizedConfig
import yaml

pid = int(sys.argv[1])
outdir = sys.argv[2]
coordinator = sys.argv[3]
tag = sys.argv[4]

multi = distributed.initialize(coordinator, num_processes=2, process_id=pid)
assert multi, "expected a multi-process world"

with open(os.path.join(outdir, "config.yaml")) as f:
    config = yaml.safe_load(f)
norm = NormalizedConfig(config, project_name="dist-test")
results = BatchedModelBuilder(
    norm.machines,
    output_dir=os.path.join(outdir, "models"),
    model_register_dir=os.path.join(outdir, "registry"),
).build()

rows = [
    [
        m.name,
        (m.metadata.user_defined or {{}}).get("build-metadata", {{}})
        == {{"from_cache": True}},
    ]
    for _, m in results
]
with open(os.path.join(outdir, "resume-{{}}-{{}}.json".format(tag, pid)), "w") as f:
    json.dump(rows, f)
print("worker", pid, tag, "done", flush=True)
"""


def _run_resume_workers(outdir: str, tag: str) -> list:
    worker_py = os.path.join(outdir, "worker_resume.py")
    with open(worker_py, "w") as f:
        f.write(WORKER_RESUME.format(repo=REPO))
    coordinator = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items() if not k.startswith("XLA_FLAGS")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker_py, str(pid), outdir, coordinator, tag],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in range(2)
    ]
    outputs = [p.communicate(timeout=600)[0] for p in procs]
    for p, out in zip(procs, outputs):
        assert p.returncode == 0, f"worker failed:\n{out[-4000:]}"
    manifests = []
    for pid in range(2):
        with open(os.path.join(outdir, f"resume-{tag}-{pid}.json")) as f:
            manifests.append(json.load(f))
    return manifests


def test_multiprocess_cache_resume():
    """Second 2-process run of the same fleet: every machine comes from
    cache, is returned by exactly ONE process, and both processes share the
    load — the ownership rule that keeps reporters from firing twice."""
    outdir = tempfile.mkdtemp(prefix="gordo-dist-resume-")
    with open(os.path.join(outdir, "config.yaml"), "w") as f:
        yaml.safe_dump(CONFIG, f)

    first = _run_resume_workers(outdir, "first")
    built = [name for m in first for name, _ in m]
    expected = sorted([f"dist-m{i}" for i in range(N_MACHINES)] + ["dist-kfold"])
    assert sorted(built) == expected
    assert not any(cached for m in first for _, cached in m)

    second = _run_resume_workers(outdir, "second")
    resumed = [name for m in second for name, _ in m]
    assert sorted(resumed) == sorted(built)
    assert len(resumed) == len(set(resumed))  # exactly one owner each
    assert all(cached for m in second for _, cached in m)
    assert all(len(m) > 0 for m in second)  # both processes own a share
