"""Version grammar tests (reference: tests/gordo/util/test_version.py)."""

import pytest

from gordo_tpu.util.version import (
    GordoPR,
    GordoRelease,
    GordoSHA,
    GordoSpecial,
    parse_version,
)


@pytest.mark.parametrize(
    "value,expected",
    [
        ("latest", GordoSpecial("latest")),
        ("stable", GordoSpecial("stable")),
        ("pr-42", GordoPR(42)),
        ("1", GordoRelease(1)),
        ("1.2", GordoRelease(1, 2)),
        ("1.2.3", GordoRelease(1, 2, 3)),
        ("1.2.3-rc1", GordoRelease(1, 2, 3, "-rc1")),
        ("1.2.3.dev1", GordoRelease(1, 2, 3, ".dev1")),
        ("deadbeefcafe", GordoSHA("deadbeefcafe")),
    ],
)
def test_parse_version(value, expected):
    parsed = parse_version(value)
    assert parsed == expected
    assert parsed.get_version() == value


def test_release_shape_predicates():
    assert GordoRelease(1).only_major()
    assert GordoRelease(1, 2).only_major_minor()
    assert not GordoRelease(1, 2, 3).only_major()


@pytest.mark.parametrize("bad", ["", "???", "v", "pr-", "xyz!"])
def test_parse_version_invalid(bad):
    with pytest.raises(ValueError):
        parse_version(bad)
