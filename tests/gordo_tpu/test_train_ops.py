"""
The training engine's two epoch programs (ops/train.py) must be the same
math: the mask-padded, live-steps-bounded epoch (the fused CV program's
body, rewritten to a lax.while_loop in round 4) against the plain scan
epoch, and against itself across n_valid values.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gordo_tpu.models.models import AutoEncoder, LSTMAutoEncoder
from gordo_tpu.ops.nn import init_model_params
from gordo_tpu.ops.train import (
    make_epoch_fn,
    make_masked_epoch_fn,
    make_optimizer,
)


def _setup(est, n_rows=96, n_tags=4, seed=0):
    spec = est.build_spec(n_tags, n_tags)
    rng = np.random.RandomState(seed)
    X = jnp.asarray(rng.rand(n_rows, n_tags).astype(np.float32))
    params = init_model_params(jax.random.PRNGKey(seed), spec)
    opt_state = make_optimizer(spec.optimizer).init(params)
    return spec, params, opt_state, X


@pytest.mark.parametrize(
    "est",
    [
        AutoEncoder(kind="feedforward_hourglass"),
        LSTMAutoEncoder(
            kind="lstm_symmetric", dims=[8], funcs=["tanh"], lookback_window=8
        ),
    ],
    ids=["dense", "windowed"],
)
def test_masked_epoch_fully_live_matches_plain_epoch(est):
    """With n_valid == n_max and shuffle off, the masked while_loop epoch
    must reproduce the plain scan epoch to fusion-level precision (XLA
    compiles the two bodies differently, so last-ulp reassociation is
    expected) — the live-steps bound changes the schedule, never the
    math."""
    from gordo_tpu.ops.train import n_train_samples

    spec, params, opt_state, X = _setup(est)
    n = n_train_samples(spec, X.shape[0])
    batch = 32
    rng_key = jax.random.PRNGKey(7)

    plain = jax.jit(make_epoch_fn(spec, n, batch, shuffle=False))
    masked = jax.jit(make_masked_epoch_fn(spec, n, batch, shuffle=False))

    p1, o1, loss1 = plain(params, opt_state, X, X, rng_key)
    p2, o2, loss2 = masked(params, opt_state, X, X, rng_key, jnp.asarray(n))

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )


def test_masked_epoch_short_fold_ignores_rows_past_prefix():
    """A fold's epoch must see exactly its train-prefix rows: poisoning the
    rows past n_valid with huge values must not change params or loss."""
    est = AutoEncoder(kind="feedforward_hourglass")
    spec, params, opt_state, X = _setup(est)
    n_max = X.shape[0]
    n_valid = 40
    masked = jax.jit(make_masked_epoch_fn(spec, n_max, 32, shuffle=True))
    rng_key = jax.random.PRNGKey(3)

    p1, _, loss1 = masked(params, opt_state, X, X, rng_key, jnp.asarray(n_valid))
    X_poison = X.at[n_valid:].set(1e6)
    p2, _, loss2 = masked(
        params, opt_state, X_poison, X_poison, rng_key, jnp.asarray(n_valid)
    )
    assert float(loss1) == float(loss2)
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_masked_epoch_loss_is_live_sample_mean():
    """The returned loss averages over live samples only (weight-padded
    batches contribute nothing)."""
    est = AutoEncoder(kind="feedforward_hourglass")
    spec, params, opt_state, X = _setup(est)
    masked = jax.jit(make_masked_epoch_fn(spec, X.shape[0], 32, shuffle=False))
    rng_key = jax.random.PRNGKey(1)
    # n_valid=33: two steps run (33 -> ceil(33/32)=2), second has 1 live row
    _, _, loss = masked(params, opt_state, X, X, rng_key, jnp.asarray(33))
    assert np.isfinite(float(loss))

    # equivalent direct computation on the first 33 rows, batch order fixed
    from gordo_tpu.ops.train import _loss_terms

    l1 = _loss_terms(spec, params, X[:32], X[:32], jnp.ones(32))
    # second step trains on updated params; just sanity-bound the epoch loss
    assert 0.0 < float(loss) < 10 * float(l1) + 1.0
