"""
Client tests — run against the real server app in-process via WSGISession
(the reference runs gordo-client against a replayed Flask test client,
tests/gordo/client/test_client.py + tests/conftest.py:356-440).
"""

import pandas as pd
import pytest

from gordo_tpu.client import Client, PredictionResult
from gordo_tpu.client.forwarders import ForwardPredictionsToDisk
from gordo_tpu.client.io import (
    BadGordoRequest,
    HttpUnprocessableEntity,
    NotFound,
    ResourceGone,
    ServerBusy,
    _handle_response,
    call_with_retry_after,
)
from gordo_tpu.client.testing import WSGISession
from gordo_tpu.server import build_app
from gordo_tpu.server import utils as server_utils


@pytest.fixture(scope="module")
def app(model_collection_directory, trained_model_directories):
    server_utils.clear_model_caches()
    return build_app({"MODEL_COLLECTION_DIR": model_collection_directory})


@pytest.fixture
def client(app, gordo_project):
    return Client(
        project=gordo_project,
        session=WSGISession(app),
        batch_size=500,
        parallelism=2,
    )


def test_client_get_machines(client, gordo_name, second_gordo_name):
    names = client.get_machine_names()
    assert set(names) == {gordo_name, second_gordo_name}


def test_client_get_revisions(client, gordo_revision):
    revisions = client.get_revisions()
    assert gordo_revision in revisions["available-revisions"]
    assert revisions["latest"] == gordo_revision


def test_client_get_metadata(client, gordo_name):
    metadata = client.get_metadata()
    assert gordo_name in metadata
    assert metadata[gordo_name]["name"] == gordo_name
    assert "dataset" in metadata[gordo_name]
    # filtering by target
    only = client.get_metadata(targets=[gordo_name])
    assert list(only) == [gordo_name]


def test_client_metadata_unknown_target(client):
    with pytest.raises(NotFound):
        client.get_metadata(targets=["no-such-machine"])


def test_client_download_model(client, gordo_name, sensors):
    models = client.download_model(targets=[gordo_name])
    model = models[gordo_name]
    idx = pd.date_range("2020-01-01", periods=16, freq="10min", tz="UTC")
    X = pd.DataFrame(
        [[0.5] * 4] * 16, columns=[t.name for t in sensors], index=idx
    )
    out = model.predict(X)
    assert out.shape == (16, 4)


@pytest.mark.parametrize("use_parquet", [True, False])
def test_client_predict(app, gordo_project, gordo_name, use_parquet, tmp_path):
    forwarder = ForwardPredictionsToDisk(str(tmp_path / "fwd"))
    client = Client(
        project=gordo_project,
        session=WSGISession(app),
        use_parquet=use_parquet,
        prediction_forwarder=forwarder,
    )
    results = client.predict(
        "2020-03-01T00:00:00+00:00",
        "2020-03-02T00:00:00+00:00",
        targets=[gordo_name],
    )
    assert len(results) == 1
    result = results[0]
    assert isinstance(result, PredictionResult)
    assert result.error_messages == []
    assert result.predictions is not None
    assert len(result.predictions) > 0
    assert "total-anomaly-scaled" in set(
        result.predictions.columns.get_level_values(0)
    )
    # forwarder received every batch
    forwarded = list((tmp_path / "fwd" / gordo_name).glob("*.parquet"))
    assert forwarded


def test_client_predict_unknown_target(client):
    with pytest.raises(NotFound):
        client.predict(
            "2020-03-01T00:00:00+00:00",
            "2020-03-02T00:00:00+00:00",
            targets=["nope"],
        )


def test_handle_response_errors():
    class FakeResp:
        headers = {"Content-Type": "application/json"}

        def __init__(self, status_code, payload=None):
            self.status_code = status_code
            self._payload = payload or {}
            self.content = b"{}"

        def json(self):
            return self._payload

    assert _handle_response(FakeResp(200, {"ok": 1})) == {"ok": 1}
    with pytest.raises(HttpUnprocessableEntity):
        _handle_response(FakeResp(422))
    with pytest.raises(NotFound):
        _handle_response(FakeResp(404))
    with pytest.raises(ResourceGone):
        _handle_response(FakeResp(410))
    with pytest.raises(BadGordoRequest):
        _handle_response(FakeResp(400))
    with pytest.raises(IOError):
        _handle_response(FakeResp(500))


def test_handle_response_quotes_trace_and_gateway_node():
    """Errors that crossed the gateway name both the trace id and the
    node the request landed on — together they point at the one machine
    whose /debug/flight holds the node-side subtree."""
    class RoutedResp:
        status_code = 500
        content = b"{}"
        headers = {
            "Content-Type": "application/json",
            "X-Gordo-Trace": "deadbeef" * 4,
            "X-Gordo-Gateway-Node": "node-2",
        }

        def json(self):
            return {"error": "boom"}

    with pytest.raises(IOError) as excinfo:
        _handle_response(RoutedResp())
    message = str(excinfo.value)
    assert f"[trace {'deadbeef' * 4}]" in message
    assert "[via node-2]" in message


# ----------------------------------------------- Retry-After (ISSUE 12)
class _BusyResp:
    """A 503 shaped like the server's shed gate / breaker / gateway
    no-live-nodes answers: JSON body plus a Retry-After header."""

    status_code = 503
    content = b"{}"

    def __init__(self, retry_after):
        self.headers = {"Content-Type": "application/json"}
        if retry_after is not None:
            self.headers["Retry-After"] = retry_after

    def json(self):
        return {"error": "busy"}


def test_handle_response_503_retry_after_raises_server_busy():
    with pytest.raises(ServerBusy) as excinfo:
        _handle_response(_BusyResp("3"))
    assert excinfo.value.retry_after_s == 3.0
    # HTTP-date form: still ServerBusy, horizon unknown → backoff alone
    with pytest.raises(ServerBusy) as excinfo:
        _handle_response(_BusyResp("Wed, 21 Oct 2026 07:28:00 GMT"))
    assert excinfo.value.retry_after_s is None
    # a 503 WITHOUT a horizon stays a plain IOError (no retry contract)
    with pytest.raises(IOError) as excinfo:
        _handle_response(_BusyResp(None))
    assert not isinstance(excinfo.value, ServerBusy)


def test_call_with_retry_after_bounded_and_honors_horizon():
    from gordo_tpu.util import faults

    policy = faults.FaultPolicy(
        max_attempts=3, backoff_base=0.1, backoff_factor=2.0,
        backoff_max=5.0, jitter=0.0,
    )
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ServerBusy("busy", retry_after_s=2.0)
        return "ok"

    assert call_with_retry_after(flaky, policy, sleep=sleeps.append) == "ok"
    assert len(calls) == 3
    # the server's horizon dominates the (shorter) exponential backoff
    assert sleeps == [2.0, 2.0]

    # bounded: max_attempts exhausted re-raises the last ServerBusy
    calls.clear()
    sleeps.clear()

    def always_busy():
        calls.append(1)
        raise ServerBusy("busy", retry_after_s=0.5)

    with pytest.raises(ServerBusy):
        call_with_retry_after(always_busy, policy, sleep=sleeps.append)
    assert len(calls) == policy.max_attempts
    assert len(sleeps) == policy.max_attempts - 1


def test_call_with_retry_after_caps_server_horizon():
    """A server cannot park the client for minutes: the Retry-After
    horizon is capped at the policy's backoff ceiling."""
    from gordo_tpu.util import faults

    policy = faults.FaultPolicy(
        max_attempts=2, backoff_base=0.1, backoff_factor=2.0,
        backoff_max=1.5, jitter=0.0,
    )
    sleeps = []

    def once_busy():
        if not sleeps:
            raise ServerBusy("busy", retry_after_s=600.0)
        return "ok"

    assert call_with_retry_after(once_busy, policy, sleep=sleeps.append) == "ok"
    assert sleeps == [1.5]


def test_client_retries_503_with_retry_after(
    app, gordo_project, gordo_name, monkeypatch
):
    """End to end through Client._post_to: a shed 503 naming Retry-After
    is retried (body rebuilt per attempt) and the retry's 200 wins."""
    monkeypatch.setenv("GORDO_TPU_FAULT_BACKOFF_BASE", "0.01")
    state = {"calls": 0}
    real_post = WSGISession.post

    def flaky_post(self, url, **kwargs):
        resp = real_post(self, url, **kwargs)
        if "/prediction" in url:
            state["calls"] += 1
            if state["calls"] == 1:
                resp.status_code = 503
                resp.headers["Retry-After"] = "0"
        return resp

    monkeypatch.setattr(WSGISession, "post", flaky_post)
    client = Client(project=gordo_project, session=WSGISession(app))
    results = client.predict(
        "2020-03-01T00:00:00+00:00",
        "2020-03-02T00:00:00+00:00",
        targets=[gordo_name],
    )
    assert state["calls"] >= 2  # first answer shed, retry served
    assert len(results) == 1
    assert results[0].error_messages == []
    assert results[0].predictions is not None


def test_client_cli_metadata(app, gordo_project, gordo_name, monkeypatch, tmp_path):
    from click.testing import CliRunner

    import gordo_tpu.client.cli as client_cli

    def patched_client(**kwargs):
        kwargs.pop("session", None)
        return Client(session=WSGISession(app), **kwargs)

    monkeypatch.setattr(client_cli, "Client", patched_client)
    out = tmp_path / "meta.json"
    runner = CliRunner()
    result = runner.invoke(
        client_cli.gordo_client,
        [
            "--project",
            gordo_project,
            "metadata",
            "--target",
            gordo_name,
            "--output-file",
            str(out),
        ],
    )
    assert result.exit_code == 0, result.output
    import json

    assert gordo_name in json.loads(out.read_text())


def test_fan_out_first_failure_cancels_unstarted_and_raises_promptly():
    """_fan_out's docstring promises: the first failure cancels the
    unstarted remainder and propagates promptly, instead of draining every
    queued doomed request (each with retry backoff) before raising."""
    import threading
    import time

    client = Client(project="p", session=object())
    client.parallelism = 2
    started: list = []
    lock = threading.Lock()

    def fetch(name):
        with lock:
            started.append(name)
        if name == "m-0":
            raise RuntimeError("boom")
        time.sleep(0.2)
        return name

    names = [f"m-{i}" for i in range(40)]
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="boom"):
        client._fan_out(fetch, names)
    elapsed = time.monotonic() - t0
    # prompt: nowhere near the ~4s a full drain of 40 x 0.2s / 2 workers
    # would take
    assert elapsed < 2.0, f"failure propagated slowly ({elapsed:.1f}s)"
    # unstarted fetches were cancelled, not run
    assert len(started) < len(names)


def test_client_calls_carry_timeout(monkeypatch):
    """Every session call carries the (connect, read) timeout — a hung
    server must hit the read timeout instead of blocking a fleet download
    forever (urllib3's Retry never fires if no response ever arrives)."""
    from gordo_tpu.client.client import DEFAULT_TIMEOUT, _timeout_from_env

    captured = []

    class StubResp:
        status_code = 200
        headers = {"Content-Type": "application/json"}
        content = b"{}"

        def json(self):
            return {"models": ["m-0"]}

    class StubSession:
        def get(self, url, params=None, timeout=None, **kwargs):
            captured.append(timeout)
            return StubResp()

    client = Client(project="p", session=StubSession())
    client.get_available_machines()
    client.get_metadata(targets=["m-0"])  # through the _fan_out fetchers
    assert captured and all(t == DEFAULT_TIMEOUT for t in captured)

    # env-configurable: "connect,read" or a single number for both
    monkeypatch.setenv("GORDO_TPU_CLIENT_TIMEOUT", "5,60")
    assert _timeout_from_env() == (5.0, 60.0)
    assert Client(project="p", session=StubSession()).timeout == (5.0, 60.0)
    monkeypatch.setenv("GORDO_TPU_CLIENT_TIMEOUT", "7")
    assert _timeout_from_env() == (7.0, 7.0)
    monkeypatch.setenv("GORDO_TPU_CLIENT_TIMEOUT", "bogus")
    assert _timeout_from_env() == DEFAULT_TIMEOUT
    # explicit constructor arg wins over env
    assert Client(
        project="p", session=StubSession(), timeout=3.0
    ).timeout == (3.0, 3.0)


def test_influx_forwarder_writes_line_protocol():
    """ForwardPredictionsIntoInflux speaks the 1.x HTTP write API directly
    (line protocol, no client library); stub session, no network."""
    import numpy as np
    import pandas as pd

    from gordo_tpu.client.forwarders import ForwardPredictionsIntoInflux

    posts = []

    class StubResp:
        status_code = 204
        text = ""

    class StubSession:
        def post(self, url, params=None, data=None, headers=None):
            posts.append((url, params, data))
            return StubResp()

    fwd = ForwardPredictionsIntoInflux(
        destination_influx_uri="influx.example:8086/proj-db",
        session=StubSession(),
    )
    idx = pd.date_range("2020-01-01", periods=3, freq="10min", tz="UTC")
    frame = pd.DataFrame(
        {
            ("start", ""): [t.isoformat() for t in idx],
            ("total-anomaly-scaled", ""): [0.1, np.nan, 0.3],
            ("tag-anomaly-unscaled", "tag one"): [1.0, 2.0, 3.0],
        },
        index=idx,
    )
    frame.columns = pd.MultiIndex.from_tuples(frame.columns)
    fwd.forward(frame, "machine a", {})

    # database created, then one write
    create_url, create_params, _ = posts[0]
    assert create_url.endswith("/query")
    assert create_params["q"] == 'CREATE DATABASE "proj-db"'
    write_url, write_params, body = posts[-1]
    assert write_url == "http://influx.example:8086/write"
    assert write_params == {"db": "proj-db", "precision": "ns"}
    lines = body.decode().splitlines()
    # string block skipped; NaN row skipped for the scalar block
    scaled = [l for l in lines if l.startswith("total-anomaly-scaled")]
    unscaled = [l for l in lines if l.startswith("tag-anomaly-unscaled")]
    assert len(scaled) == 2 and len(unscaled) == 3
    assert not any(l.startswith("start") for l in lines)
    # escaping: machine tag space, field-key space, ns timestamp
    assert scaled[0] == (
        f"total-anomaly-scaled,machine=machine\\ a value=0.1 {idx[0].value}"
    )
    assert "tag\\ one=1.0" in unscaled[0]


def test_influx_forwarder_lazy_session_no_deadlock(monkeypatch):
    """The production path constructs the forwarder WITHOUT a session
    (client/cli.py): the first forward() creates one lazily while the
    prepare lock is held — this must not self-deadlock (RLock), and
    concurrent forwards must run DROP/CREATE exactly once."""
    import threading

    import pandas as pd
    import requests

    from gordo_tpu.client.forwarders import ForwardPredictionsIntoInflux

    posts = []

    class StubResp:
        status_code = 204
        text = ""

    class StubSession:
        def post(self, url, params=None, data=None, headers=None):
            posts.append((url, params))
            return StubResp()

    monkeypatch.setattr(requests, "Session", StubSession)
    fwd = ForwardPredictionsIntoInflux(
        destination_influx_uri="influx.example:8086/proj-db",
        destination_influx_recreate=True,
    )
    idx = pd.date_range("2020-01-01", periods=2, freq="10min", tz="UTC")
    frame = pd.DataFrame({("prediction", "t0"): [0.1, 0.2]}, index=idx)

    done = []

    def run():
        fwd(frame, "m", {})
        done.append(1)

    threads = [threading.Thread(target=run) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(done) == 4, "forward() deadlocked or failed"
    drops = [p for p in posts if p[1] and "DROP" in str(p[1].get("q", ""))]
    creates = [p for p in posts if p[1] and "CREATE" in str(p[1].get("q", ""))]
    assert len(drops) == 1 and len(creates) == 1
