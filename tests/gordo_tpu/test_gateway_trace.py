"""
Gateway trace plane: traceparent propagation, cross-node stitching,
exemplar-linked metrics (the fleet-trace ISSUE).

- **Propagation**: a routed request's ``traceparent`` reaches the node
  with the SAME trace id but a NEW parent span (the gateway's upstream
  attempt span), over both lanes — pooled TCP keep-alive and the
  Unix-domain fast lane — and every request on a reused (pipelined)
  upstream connection carries its own, not a stale neighbour's.
- **Stitching**: ``GET /debug/flight?trace=<id>`` on the gateway grafts
  each upstream node's subtree into one Chrome-trace document; a node
  dying mid-fetch (torn stitch) degrades to an explicit ``gordoStitch``
  entry, never an error.
- **Exemplars**: the gateway's /metrics carries OpenMetrics exemplars
  whose trace ids resolve against the same /debug/flight surface.
- **Hot path**: with tracing off (no inbound traceparent, knob unset)
  the gateway allocates NOTHING in the tracing/flight modules —
  tracemalloc-pinned, so the trace plane stays opt-in for free.
"""

import http.client
import json
import re
import socket
import threading
import time
import tracemalloc
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import pytest

from gordo_tpu.observability import tracing
from gordo_tpu.server import gateway, membership
from gordo_tpu.util import faults


def _make_gateway(tmp_path) -> gateway.GatewayServer:
    return gateway.GatewayServer(str(tmp_path), host="127.0.0.1", port=0)


def _gateway_request(server, method, path, headers=None, timeout=10):
    conn = http.client.HTTPConnection(
        "127.0.0.1", server.server_port, timeout=timeout
    )
    try:
        conn.request(method, path, headers=headers or {})
        resp = conn.getresponse()
        data = resp.read()
        return resp.status, {k.lower(): v for k, v in resp.getheaders()}, data
    finally:
        conn.close()


class _TraceStubNode:
    """A fake serving node that RECORDS every inbound ``traceparent`` and
    answers ``/debug/flight?trace=<id>`` like a real node's debug surface:
    a canned serve_request subtree for traces it saw, 404 for the rest.
    ``tear_debug=True`` severs the connection on the debug route instead —
    the node dying mid-fetch."""

    def __init__(self, directory: str, node_id: str, tear_debug=False):
        self.node_id = node_id
        self.traceparents = []
        self.tear_debug = tear_debug
        node = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _answer(self):
                path, _, query = self.path.partition("?")
                if path == "/debug/flight":
                    return self._flight(query)
                node.traceparents.append(self.headers.get("traceparent"))
                body = json.dumps(
                    {"node": node.node_id, "path": self.path}
                ).encode()
                self._reply(200, body)

            def _flight(self, query):
                if node.tear_debug:
                    # die mid-fetch: no status line, just a severed socket
                    self.connection.shutdown(socket.SHUT_RDWR)
                    self.close_connection = True
                    return
                trace_id = None
                for part in query.split("&"):
                    name, _, value = part.partition("=")
                    if name == "trace":
                        trace_id = value
                seen = [
                    tracing.parse_traceparent(tp)
                    for tp in node.traceparents if tp
                ]
                match = next(
                    (pair for pair in seen if pair and pair[0] == trace_id),
                    None,
                )
                if match is None:
                    self._reply(404, json.dumps(
                        {"error": "trace not kept"}
                    ).encode())
                    return
                trace_id, parent_span = match
                doc = {
                    "traceEvents": [{
                        "name": "serve_request", "ph": "X", "ts": 0,
                        "dur": 1000, "pid": 1, "tid": 1,
                        "args": {
                            "trace_id": trace_id,
                            "span_id": "feedface00000001",
                            "parent_span_id": parent_span,
                        },
                    }],
                    "gordoFlight": [{"trace_id": trace_id}],
                }
                self._reply(200, json.dumps(doc).encode())

            def _reply(self, status, body):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_GET = do_POST = _answer

            def log_message(self, *args):  # silence
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()
        self.registration = membership.NodeRegistration(
            directory,
            address=f"127.0.0.1:{self.port}",
            node_id=node_id,
        )

    def close(self):
        self.registration.close()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.thread.join(timeout=2.0)


@pytest.fixture
def traced_fleet(tmp_path, monkeypatch):
    """One stub node + gateway, lease/health knobs tightened for tests;
    debug endpoints on so the stitching surface is reachable."""
    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "2.5")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.2")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_HEALTH_S", "0.3")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_CONNECT_TIMEOUT_S", "0.5")
    monkeypatch.setenv("GORDO_TPU_DEBUG_ENDPOINTS", "1")
    faults.reset_plan()
    node = _TraceStubNode(str(tmp_path), "node-a")
    server = _make_gateway(tmp_path)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    deadline = time.monotonic() + 5.0
    while not server.ring.nodes and time.monotonic() < deadline:
        time.sleep(0.05)
    assert server.ring.nodes
    yield SimpleNamespace(server=server, node=node)
    server.shutdown()
    server.server_close()
    thread.join(timeout=5)
    node.close()


def _traced_headers():
    trace_id = tracing.new_trace_id()
    span_id = tracing.new_span_id()
    return trace_id, span_id, {"traceparent": f"00-{trace_id}-{span_id}-01"}


# ----------------------------------------------------------- propagation
def test_traceparent_continues_with_new_parent_over_tcp(traced_fleet):
    """The node receives the caller's trace id under a NEW parent span —
    the gateway's attempt span — so node-side serve_request trees hang
    under the hedge arm that actually carried them."""
    trace_id, span_id, headers = _traced_headers()
    status, out_headers, _ = _gateway_request(
        traced_fleet.server, "GET", "/gordo/v0/proj/m-1/metadata",
        headers=headers,
    )
    assert status == 200
    assert out_headers["x-gordo-trace"] == trace_id
    assert "gateway_s;dur=" in out_headers["server-timing"]
    seen = [tp for tp in traced_fleet.node.traceparents if tp]
    assert seen, "node never saw a traceparent"
    got_trace, got_parent = tracing.parse_traceparent(seen[-1])
    assert got_trace == trace_id
    assert got_parent != span_id  # re-parented under the attempt span


def test_pipelined_keepalive_requests_each_carry_own_traceparent(
    traced_fleet,
):
    """Three traced requests for the same machine ride the same pooled
    upstream keep-alive connection — each must carry ITS trace id, not a
    stale neighbour's from the reused connection."""
    server = traced_fleet.server
    sent = []
    conn = http.client.HTTPConnection(
        "127.0.0.1", server.server_port, timeout=10
    )
    try:
        for _ in range(3):
            trace_id, _, headers = _traced_headers()
            sent.append(trace_id)
            conn.request(
                "GET", "/gordo/v0/proj/m-1/metadata", headers=headers
            )
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 200
            assert resp.headers["X-Gordo-Trace"] == trace_id
    finally:
        conn.close()
    received = [
        tracing.parse_traceparent(tp)[0]
        for tp in traced_fleet.node.traceparents if tp
    ]
    assert received[-3:] == sent


def _recording_wsgi_app(record):
    def app(environ, start_response):
        record.append(environ.get("HTTP_TRACEPARENT"))
        body = json.dumps({"node": "uds-only"}).encode()
        start_response(
            "200 OK",
            [("Content-Type", "application/json"),
             ("Content-Length", str(len(body)))],
        )
        return [body]
    return app


def test_traceparent_propagates_over_uds_lane(tmp_path, monkeypatch):
    """Same continuation contract on the Unix-domain lane: the lease's
    TCP address is dead, so the traceparent can only have traveled UDS —
    and keep-alive reuse of that lane keeps per-request ids distinct."""
    from gordo_tpu.server import fastlane

    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "2.5")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.2")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_HEALTH_S", "5.0")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_CONNECT_TIMEOUT_S", "0.5")
    received = []
    sock_path = str(tmp_path / "node-uds.sock")
    node = fastlane.EventLoopServer(
        _recording_wsgi_app(received), host="127.0.0.1", port=0,
        uds=sock_path,
    )
    node_thread = threading.Thread(target=node.serve_forever, daemon=True)
    node_thread.start()
    registration = membership.NodeRegistration(
        str(tmp_path), address="127.0.0.1:1",  # dead TCP: UDS or bust
        node_id="node-uds", uds=sock_path,
    )
    server = _make_gateway(tmp_path)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        deadline = time.monotonic() + 5.0
        while not server.ring.nodes and time.monotonic() < deadline:
            time.sleep(0.05)
        sent = []
        for _ in range(3):
            trace_id, span_id, headers = _traced_headers()
            sent.append((trace_id, span_id))
            status, out_headers, _ = _gateway_request(
                server, "GET", "/gordo/v0/proj/m-1/metadata",
                headers=headers,
            )
            assert status == 200
            assert out_headers["x-gordo-trace"] == trace_id
        got = [tracing.parse_traceparent(tp) for tp in received if tp]
        assert [pair[0] for pair in got] == [pair[0] for pair in sent]
        for (_, client_span), (_, node_parent) in zip(sent, got):
            assert node_parent != client_span  # re-parented at gateway
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=5)
        registration.close()
        node.server_close()
        node_thread.join(timeout=5)


# ------------------------------------------------------------- stitching
def test_stitched_flight_grafts_node_subtree(traced_fleet):
    """/debug/flight?trace= returns ONE document: the gateway's own span
    tree plus the node's serve_request subtree, tagged with the node id
    and parented (by span ids) under the gateway's attempt span."""
    server, node = traced_fleet.server, traced_fleet.node
    trace_id, _, headers = _traced_headers()
    status, _, _ = _gateway_request(
        server, "GET", "/gordo/v0/proj/m-1/metadata", headers=headers
    )
    assert status == 200
    status, _, body = _gateway_request(
        server, "GET", f"/debug/flight?trace={trace_id}"
    )
    assert status == 200, body[:300]
    doc = json.loads(body)
    names = {e["name"] for e in doc["traceEvents"]}
    assert "gateway_request" in names
    assert "gateway_upstream_attempt" in names
    assert "serve_request" in names
    stitch = doc["gordoStitch"]
    assert stitch["trace_id"] == trace_id
    assert stitch["complete"] is True
    assert stitch["nodes"] == [
        {"node": "node-a", "ok": True, "events": 1}
    ]
    grafted = next(
        e for e in doc["traceEvents"] if e["name"] == "serve_request"
    )
    assert grafted["args"]["gordo_node"] == "node-a"
    attempts = {
        e["args"]["span_id"]
        for e in doc["traceEvents"]
        if e["name"] == "gateway_upstream_attempt"
    }
    assert grafted["args"]["parent_span_id"] in attempts


def test_stitched_flight_unknown_trace_is_404(traced_fleet):
    status, _, body = _gateway_request(
        traced_fleet.server, "GET", f"/debug/flight?trace={'0' * 32}"
    )
    assert status == 404
    assert b"not kept" in body


def test_torn_stitch_node_dies_mid_fetch(tmp_path, monkeypatch):
    """A node severing the connection during the subtree fetch (torn
    stitch) degrades to an explicit partial: the gateway's own subtree
    still returns 200, with the loss named in gordoStitch."""
    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "2.5")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.2")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_HEALTH_S", "0.3")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_CONNECT_TIMEOUT_S", "0.5")
    monkeypatch.setenv("GORDO_TPU_DEBUG_ENDPOINTS", "1")
    node = _TraceStubNode(str(tmp_path), "node-torn", tear_debug=True)
    server = _make_gateway(tmp_path)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        deadline = time.monotonic() + 5.0
        while not server.ring.nodes and time.monotonic() < deadline:
            time.sleep(0.05)
        trace_id, _, headers = _traced_headers()
        status, _, _ = _gateway_request(
            server, "GET", "/gordo/v0/proj/m-1/metadata", headers=headers
        )
        assert status == 200
        status, _, body = _gateway_request(
            server, "GET", f"/debug/flight?trace={trace_id}"
        )
        assert status == 200, body[:300]
        doc = json.loads(body)
        assert {e["name"] for e in doc["traceEvents"]} >= {
            "gateway_request", "gateway_upstream_attempt"
        }
        stitch = doc["gordoStitch"]
        assert stitch["complete"] is False
        (entry,) = stitch["nodes"]
        assert entry["node"] == "node-torn"
        assert entry["ok"] is False
        assert "unreachable" in entry["reason"]
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=5)
        node.close()


# ------------------------------------------------------------- exemplars
_EXEMPLAR_RE = re.compile(r'# \{trace_id="([0-9a-f]{32})"\}')


def test_metrics_exemplar_trace_id_resolves_via_debug_flight(traced_fleet):
    """The loop an operator actually walks: a bucket's exemplar on the
    gateway's /metrics names a trace id, and that id resolves against the
    SAME gateway's /debug/flight?trace= to the full routed tree."""
    server = traced_fleet.server
    trace_id, _, headers = _traced_headers()
    status, _, _ = _gateway_request(
        server, "GET", "/gordo/v0/proj/m-1/metadata", headers=headers
    )
    assert status == 200
    status, _, exposition = _gateway_request(server, "GET", "/metrics")
    assert status == 200
    exemplar_ids = set(_EXEMPLAR_RE.findall(exposition.decode()))
    assert trace_id in exemplar_ids
    status, _, body = _gateway_request(
        server, "GET", f"/debug/flight?trace={trace_id}"
    )
    assert status == 200
    assert json.loads(body)["gordoStitch"]["trace_id"] == trace_id


# --------------------------------------------------------------- hot path
def test_untraced_path_allocates_nothing_in_trace_modules(traced_fleet):
    """With no inbound traceparent and GORDO_TPU_GATEWAY_TRACE unset, the
    routed path must make ZERO allocations in the tracing and flight
    modules — the trace plane is opt-in, priced only when bought."""
    server = traced_fleet.server
    assert not server.trace_all
    # warm the pooled upstream connection and any lazy codepaths first
    for _ in range(3):
        status, _, _ = _gateway_request(
            server, "GET", "/gordo/v0/proj/m-1/metadata"
        )
        assert status == 200
    trace_filters = [
        tracemalloc.Filter(True, "*/observability/tracing.py"),
        tracemalloc.Filter(True, "*/observability/flight.py"),
    ]
    tracemalloc.start(10)
    try:
        before = tracemalloc.take_snapshot().filter_traces(trace_filters)
        for _ in range(5):
            status, _, _ = _gateway_request(
                server, "GET", "/gordo/v0/proj/m-1/metadata"
            )
            assert status == 200
        after = tracemalloc.take_snapshot().filter_traces(trace_filters)
    finally:
        tracemalloc.stop()
    grown = [
        stat for stat in after.compare_to(before, "filename")
        if stat.size_diff > 0 or stat.count_diff > 0
    ]
    assert not grown, f"untraced path touched trace modules: {grown}"
