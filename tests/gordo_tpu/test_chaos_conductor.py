"""
Chaos conductor tests (ISSUE 16): scenario schema validation, the
machine-checked invariant vocabulary on synthetic run contexts, and one
tiny end-to-end drill (2 nodes, kill one mid-load) — the committed
scenarios under resources/chaos/ are the full-size drills; this keeps
the conductor's contract pinned at tier-1 speed.
"""

import json
import os
import tempfile

import pytest

from gordo_tpu.chaos import invariants as inv
from gordo_tpu.chaos import scenario as scn
from gordo_tpu.chaos.conductor import run_scenario
from gordo_tpu.observability.latency import LatencyHistogram
from gordo_tpu.server import resilience


# ------------------------------------------------------ scenario schema
def _minimal_doc(**overrides):
    doc = {
        "name": "unit",
        "stack": {"nodes": 2},
        "machines": 4,
        "load": {"phases": [{"shape": "flat", "qps": 10, "duration": 1}]},
        "invariants": [{"check": "availability", "min": 0.9}],
    }
    doc.update(overrides)
    return doc


def test_parse_scenario_minimal():
    spec = scn.parse_scenario(_minimal_doc())
    assert spec.name == "unit"
    assert spec.nodes == 2
    assert spec.machines == ["m-000", "m-001", "m-002", "m-003"]
    assert len(spec.phases) == 1 and spec.phases[0].shape == "flat"
    assert spec.invariants[0].check == "availability"
    assert spec.invariants[0].params == {"min": 0.9}


@pytest.mark.parametrize(
    "mutation",
    [
        {"load": {"phases": [{"shape": "sawtooth", "qps": 10, "duration": 1}]}},
        {"load": {"phases": [{"shape": "flat", "qps": 10, "duration": 1,
                              "bogus_knob": 3}]}},
        {"timeline": [{"at": 0.5, "action": "reboot_node", "node": 0}]},
        {"timeline": [{"at": 0.5, "action": "kill_node", "node": 7}]},
        {"timeline": [{"at": 2.0, "action": "kill_node", "node": 0},
                      {"at": 1.0, "action": "stop_node", "node": 1}]},
        {"invariants": [{"check": "always_fine"}]},
        {"fault_plan": {"rules": [{"site": "not_a_site", "times": 1,
                                   "error": "transient"}]}},
        {"load": {"phases": [{"shape": "flat", "qps": 10, "duration": 1}],
                  "chaff": [{"kind": "udp_flood", "conns": 2,
                             "duration": 1}]}},
    ],
)
def test_parse_scenario_rejects_bad_vocabulary(mutation):
    with pytest.raises(scn.ScenarioError):
        scn.parse_scenario(_minimal_doc(**mutation))


def test_load_scenario_json_and_yaml(tmp_path):
    doc = _minimal_doc()
    jpath = tmp_path / "s.json"
    jpath.write_text(json.dumps(doc))
    assert scn.load_scenario(str(jpath)).name == "unit"
    ypath = tmp_path / "s.yaml"
    ypath.write_text(
        "name: unit\nstack: {nodes: 2}\nmachines: 4\n"
        "load:\n  phases:\n    - {shape: flat, qps: 10, duration: 1}\n"
        "invariants:\n  - {check: availability, min: 0.9}\n"
    )
    assert scn.load_scenario(str(ypath)).nodes == 2


def test_committed_scenarios_all_parse():
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    chaos_dir = os.path.join(repo, "resources", "chaos")
    files = sorted(
        f for f in os.listdir(chaos_dir)
        if f.endswith((".yaml", ".yml", ".json"))
    )
    assert len(files) >= 4, "the issue commits 4-6 scenarios"
    for name in files:
        spec = scn.load_scenario(os.path.join(chaos_dir, name))
        assert spec.invariants, f"{name} asserts nothing"


# -------------------------------------------------- invariant checkers
def _ctx(**overrides):
    """A synthetic RunContext: 10 arrivals over 2 machines, all ok."""
    log = [
        (i * 0.1, 0.005, None, f"m-{i % 2:03d}", 0) for i in range(10)
    ]
    hist = LatencyHistogram()
    for e in log:
        hist.record(e[1])
    ctx = inv.RunContext(
        log=log, hist=hist, per_phase={0: hist}, scheduled={0: 10},
        primaries={"m-000": "node-0", "m-001": "node-1"},
        actions=[], breakers={}, drift=None,
    )
    for key, value in overrides.items():
        setattr(ctx, key, value)
    return ctx


def _run(name, ctx, **params):
    results = inv.evaluate([scn.Invariant(check=name, params=params)], ctx)
    return results[0]


def test_availability_floor_and_exclude():
    assert _run("availability", _ctx(), min=1.0)["ok"]
    ctx = _ctx()
    ctx.log[0] = (0.0, 0.005, "http-503", "m-000", 0)
    assert not _run("availability", ctx, min=0.95)["ok"]
    # the failing machine excluded: back over the floor
    assert _run("availability", ctx, min=0.95, exclude=["m-000"])["ok"]


def test_zero_5xx_counts_server_and_transport_errors_only():
    ctx = _ctx()
    ctx.log[1] = (0.1, 0.005, "http-404", "m-001", 0)  # 4xx is fine
    assert _run("zero_5xx", ctx)["ok"]
    ctx.log[2] = (0.2, 0.005, "ConnectionResetError(54)", "m-000", 0)
    result = _run("zero_5xx", ctx)
    assert not result["ok"]
    assert _run("zero_5xx", ctx, max=1)["ok"]


def test_failover_under_bound():
    ctx = _ctx(actions=[
        {"action": "kill_node", "node": 0, "node_id": "node-0",
         "fired_at": 0.35},
    ])
    # m-000 (primary node-0) answers at 0.4+0.005 -> 0.055s after the kill
    result = _run("failover_under", ctx, seconds=0.5)
    assert result["ok"], result["detail"]
    assert not _run("failover_under", ctx, seconds=0.01)["ok"]
    # no kill action at all: the invariant fails loudly, not vacuously
    assert not _run("failover_under", _ctx(), seconds=5)["ok"]


def test_p99_under_merged_and_per_phase():
    assert _run("p99_under", _ctx(), ms=1000)["ok"]
    assert not _run("p99_under", _ctx(), ms=0.001)["ok"]
    assert _run("p99_under", _ctx(), ms=1000, phase=0)["ok"]


def test_breaker_scoped_blast_radius():
    tripped = {"node-0": {"m-003": resilience.OPEN,
                          "m-001": resilience.CLOSED}}
    assert _run("breaker_scoped", _ctx(breakers=tripped),
                models=["m-003"])["ok"]
    # a breaker outside the poisoned set leaked
    assert not _run("breaker_scoped", _ctx(breakers=tripped),
                    models=["m-007"])["ok"]
    # poison declared but nothing tripped: the drill proved nothing
    assert not _run("breaker_scoped", _ctx(breakers={}),
                    models=["m-003"])["ok"]


def test_histogram_exact_accounting():
    assert _run("histogram_exact", _ctx())["ok"]
    # a lost arrival (scheduled but never logged) breaks exactness
    assert not _run("histogram_exact", _ctx(scheduled={0: 11}))["ok"]
    # an error wrongly recorded into the latency histogram breaks it too
    ctx = _ctx()
    ctx.log[0] = (0.0, 0.005, "http-503", "m-000", 0)
    assert not _run("histogram_exact", ctx)["ok"]


def test_one_rebuild_per_machine_exactly_once():
    drift = {"machines": 4, "threads": 8, "enqueued": 4, "depth": 4}
    assert _run("one_rebuild_per_machine", _ctx(drift=drift))["ok"]
    dup = dict(drift, enqueued=6, depth=6)
    assert not _run("one_rebuild_per_machine", _ctx(drift=dup))["ok"]
    assert not _run("one_rebuild_per_machine", _ctx())["ok"]


def _stitched_doc(victim="node-1", survivor="node-0", with_subtree=True):
    def ev(name, span_id, parent, **attrs):
        args = {"trace_id": "ab" * 16, "span_id": span_id,
                "parent_span_id": parent}
        args.update({k: str(v) for k, v in attrs.items()})
        return {"name": name, "ph": "X", "args": args}

    events = [
        ev("gateway_request", "s-root", "", method="GET", status=200),
        ev("gateway_route_resolve", "s-rr", "s-root", machine="m-000"),
        ev("gateway_upstream_attempt", "s-a0", "s-root", node=victim,
           attempt=0, error="ConnectionRefusedError(111)"),
        ev("gateway_upstream_attempt", "s-a1", "s-root", node=survivor,
           attempt=1, status=200),
    ]
    if with_subtree:
        events += [
            ev("serve_request", "s-n0", "s-a1", node=survivor, status=200),
            ev("serve_batch_queue", "s-n1", "s-n0"),
            ev("serve_device_call", "s-n2", "s-n1"),
        ]
    return {"traceEvents": events,
            "gordoStitch": {"complete": with_subtree}}


def test_stitched_trace_checker():
    good = {"doc": _stitched_doc(), "victim": "node-1",
            "trace_id": "ab" * 16}
    result = _run("stitched_trace", _ctx(stitched=good))
    assert result["ok"], result["detail"]
    # no capture at all: fails with the conductor's reason
    missing = _run("stitched_trace",
                   _ctx(stitched={"reason": "probe never landed"}))
    assert not missing["ok"] and "probe never landed" in missing["detail"]
    # survivor subtree torn off (node died / gate off): partial is not ok
    no_tree = {"doc": _stitched_doc(with_subtree=False), "victim": "node-1"}
    assert not _run("stitched_trace", _ctx(stitched=no_tree))["ok"]
    # the failed attempt must be on the declared victim
    wrong_victim = {"doc": _stitched_doc(), "victim": "node-9"}
    assert not _run("stitched_trace", _ctx(stitched=wrong_victim))["ok"]


def test_unknown_invariant_fails_loudly():
    result = _run("definitely_not_a_check", _ctx())
    assert not result["ok"]
    assert "unknown" in result["detail"]


# ------------------------------------------------- tiny end-to-end drill
def test_conductor_tiny_drill_kill_one_node():
    """The smallest real drill: 2 subprocess nodes + in-process gateway,
    flat load, one node killed mid-window. Pins the whole conductor loop
    — stack boot, timeline firing, per-arrival accounting, invariant
    evaluation, and the stitched-trace failover capture — in a few
    seconds of tier-1 time. This is the `make chaos-smoke` contract's
    tier-1 twin (the committed scenario is the full-size drill)."""
    spec = scn.parse_scenario({
        "name": "tiny-drill",
        "seed": 1,
        "stack": {"nodes": 2, "lease_timeout_s": 1.5, "heartbeat_s": 0.15,
                  "gateway_env": {"health_s": "0.2",
                                  "connect_timeout_s": "0.5"}},
        "env": {"GORDO_TPU_DEBUG_ENDPOINTS": "1",
                "GORDO_TPU_FLIGHT_RECENT": "64"},
        "machines": 8,
        "load": {"phases": [{"shape": "flat", "qps": 25, "duration": 2.0,
                             "users": 4}]},
        "timeline": [{"at": 0.8, "action": "kill_node", "node": 1}],
        "invariants": [
            {"check": "availability", "min": 0.9},
            {"check": "failover_under", "seconds": 2.0},
            {"check": "histogram_exact"},
            {"check": "stitched_trace"},
        ],
    })
    directory = tempfile.mkdtemp(prefix="gordo-chaos-test-")
    try:
        report = run_scenario(spec, directory)
    finally:
        import shutil

        shutil.rmtree(directory, ignore_errors=True)
    assert report["ok"], report["invariants"]
    assert report["scheduled"] == 50
    assert report["availability"] >= 0.9
    assert [a["action"] for a in report["actions"]] == ["kill_node"]
    assert report["failover_s"] is not None and report["failover_s"] <= 2.0
    checks = {r["check"]: r["ok"] for r in report["invariants"]}
    assert checks == {"availability": True, "failover_under": True,
                      "histogram_exact": True, "stitched_trace": True}
    # the captured trace is quotable: the report names the id an operator
    # would pull from the gateway's /debug/flight?trace=
    assert report["stitched_trace"]["trace_id"]
    assert report["stitched_trace"]["victim"] == "node-1"


def test_chaos_smoke_scenario_is_the_committed_one():
    """`make chaos-smoke` and tier-1 must drill the same contract: the
    committed kill_node_mid_ramp.yaml declares the stitched-trace
    assertion (plus the debug/flight knobs it needs), so the Makefile
    target and CI cannot drift apart on what failover evidence means."""
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    spec = scn.load_scenario(
        os.path.join(repo, "resources", "chaos", "kill_node_mid_ramp.yaml")
    )
    assert "stitched_trace" in {i.check for i in spec.invariants}
    assert spec.env.get("GORDO_TPU_DEBUG_ENDPOINTS") == "1"
    assert int(spec.env.get("GORDO_TPU_FLIGHT_RECENT", "0")) > 0
    makefile = open(os.path.join(repo, "Makefile")).read()
    assert "kill_node_mid_ramp.yaml" in makefile
