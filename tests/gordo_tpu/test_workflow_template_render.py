"""Template render gate (round-4 verdict item 8).

Round 4 shipped a Jinja syntax error in tpu-workflow.yml.template that
killed `workflow generate` outright. This module is the cheap gate that
makes that impossible to repeat: it renders the template across the full
toggle matrix — every Jinja branch — parses every document, and runs the
structural linter (workflow/validate.py) over each rendering. Any template
edit that breaks ANY branch fails here in seconds, with no cluster.

CI runs this module on every push (`.github/workflows/main.yml`), and
`make render-gate` runs it locally.
"""

import itertools

import pytest
import yaml

from gordo_tpu.cli.workflow_generator import generate_workflow_docs
from gordo_tpu.workflow.validate import validate_workflow_docs


def _config_yaml(n_machines: int) -> str:
    machines = [
        {
            "name": f"machine-{i}",
            "dataset": {
                "type": "RandomDataset",
                "tags": [f"tag-{i}-{j}" for j in range(4)],
                "train_start_date": "2019-01-01T00:00:00+00:00",
                "train_end_date": "2019-01-08T00:00:00+00:00",
            },
            "model": {
                "gordo_tpu.models.models.AutoEncoder": {
                    "kind": "feedforward_hourglass"
                }
            },
        }
        for i in range(n_machines)
    ]
    return yaml.safe_dump({"machines": machines})


@pytest.fixture(scope="module")
def config_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("render-gate") / "config.yml"
    p.write_text(_config_yaml(3))
    return str(p)


def _render(config_file, **overrides) -> str:
    overrides.setdefault("client_start_date", "2019-01-01T00:00:00Z")
    overrides.setdefault("client_end_date", "2019-01-02T00:00:00Z")
    return generate_workflow_docs(
        machine_config=config_file, project_name="render-gate", **overrides
    )


# The boolean toggles that guard whole template sections, plus the HPA
# selector: together these drive every {% if %}/{% for %} branch. The full
# cross-product is 2^5 * 2 = 64 renderings — still a few seconds total.
_BOOL_TOGGLES = (
    "enable_clients",
    "enable_postgres",
    "enable_influx",
    "enable_grafana",
    "spot_tolerations",
)


@pytest.mark.parametrize(
    "flags",
    list(itertools.product([True, False], repeat=len(_BOOL_TOGGLES))),
    ids=lambda flags: "".join("ty"[f] for f in flags),
)
@pytest.mark.parametrize("hpa", ["cpu", "keda"])
def test_every_toggle_branch_renders_and_lints(config_file, flags, hpa):
    content = _render(
        config_file,
        ml_server_hpa_type=hpa,
        **dict(zip(_BOOL_TOGGLES, flags)),
    )
    docs = [d for d in yaml.safe_load_all(content) if d]
    assert docs, "rendering produced no documents"
    validate_workflow_docs(content)


def test_multi_chunk_and_sliced_tpu_branches(config_file):
    """The per-chunk loops and the multi-worker TPU coordination branch."""
    content = _render(
        config_file,
        machines_per_tpu_worker=1,   # 3 machines -> 3 chunks
        tpu_workers_per_slice=2,     # the coord-svc / withSequence branch
    )
    docs = [d for d in yaml.safe_load_all(content) if d]
    assert docs
    validate_workflow_docs(content)


def test_owner_refs_and_custom_envs_branches(config_file, tmp_path):
    content = _render(
        config_file,
        owner_references=(
            '[{"apiVersion": "v1", "kind": "Deployment", '
            '"name": "x", "uid": "1"}]'
        ),
        custom_model_builder_envs='[{"name": "EXTRA", "value": "1"}]',
        postgres_host="pg.example.com",
        resource_labels=(("team", "abc"),),
    )
    docs = [d for d in yaml.safe_load_all(content) if d]
    assert docs
    validate_workflow_docs(content)
