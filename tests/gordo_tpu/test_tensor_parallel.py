"""
Tensor parallelism (model-axis sharding) on the 8-virtual-device CPU mesh.

Parity contract: sharding is placement only — a TP-trained model must match
the single-device model numerically (same seed, same data) up to reduction
order, and TP specs must keep off both vmapping paths (fleet trainer,
serving batcher) the same way ring-attention specs do.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from gordo_tpu.models.models import TransformerAutoEncoder
from gordo_tpu.models.spec import TransformerBlock
from gordo_tpu.parallel.tensor_parallel import (
    prepare_tp_spec,
    shard_params_tp,
    tp_degree,
    tp_mesh,
)

N_TAGS = 4
ROWS = 96
TP_KW = dict(
    kind="transformer_model",
    lookback_window=16,
    d_model=32,
    num_heads=8,
    ff_dim=64,
    num_blocks=2,
    epochs=2,
    batch_size=32,
)


def _data():
    rng = np.random.RandomState(7)
    X = rng.rand(ROWS, N_TAGS).astype(np.float32)
    return X


def _fit(tensor_parallel: int):
    np.random.seed(123)  # fit() draws its PRNG seed from the global RNG
    model = TransformerAutoEncoder(
        tensor_parallel=tensor_parallel, **TP_KW
    )
    X = _data()
    model.fit(X, X)
    return model


def test_tp_matches_single_device():
    single = _fit(0)
    sharded = _fit(8)
    assert tp_degree(sharded.spec_) == 8
    np.testing.assert_allclose(
        single.predict(_data()), sharded.predict(_data()), rtol=2e-4, atol=2e-5
    )
    np.testing.assert_allclose(
        single.history["loss"], sharded.history["loss"], rtol=2e-4
    )


def test_tp_params_are_sharded_megatron_style():
    model = _fit(8)
    block_params = model.params_[2]  # Dense, PE, then first TransformerBlock
    def spec_of(leaf):
        return leaf.sharding.spec

    # row-parallel specs normalize their trailing None away
    assert spec_of(block_params["wq"]) == P(None, "model")
    assert spec_of(block_params["wo"]) in (P("model"), P("model", None))
    assert spec_of(block_params["w_ff1"]) == P(None, "model")
    assert spec_of(block_params["w_ff2"]) in (P("model"), P("model", None))
    assert spec_of(block_params["b_ff1"]) == P("model")
    assert spec_of(block_params["ln1_scale"]) == P()
    # attention was pinned to the partitionable impl at spec-build time
    blocks = [
        l for l in model.spec_.layers if isinstance(l, TransformerBlock)
    ]
    assert all(b.attention_impl == "xla" for b in blocks)


def test_tp_rejects_indivisible_and_unpartitionable():
    spec = TransformerAutoEncoder(**{**TP_KW, "num_heads": 4}).build_spec(
        N_TAGS, N_TAGS
    )
    spec = dataclasses.replace(spec, tensor_parallel=8)
    with pytest.raises(ValueError, match="num_heads"):
        prepare_tp_spec(spec)

    with pytest.raises(ValueError, match="cannot run tensor-parallel"):
        TransformerAutoEncoder(
            tensor_parallel=8, **{**TP_KW, "attention": "flash"}
        ).build_spec(N_TAGS, N_TAGS)

    with pytest.raises(ValueError, match="device"):
        tp_mesh(1024)


def test_tp_requires_transformer_layers():
    from gordo_tpu.models.models import AutoEncoder

    with pytest.raises(ValueError, match="TransformerBlock"):
        AutoEncoder(
            kind="feedforward_hourglass", tensor_parallel=8
        ).build_spec(N_TAGS, N_TAGS)


def test_shard_params_noop_when_off():
    model = _fit(0)
    assert shard_params_tp(model.spec_, model.params_) is model.params_


def test_tp_machines_take_serial_fallback():
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel.batch_trainer import _plan_machine

    config = {
        "name": "tp-machine",
        "dataset": {
            "type": "RandomDataset",
            "tags": [f"tp-tag-{i}" for i in range(N_TAGS)],
            "train_start_date": "2019-01-01T00:00:00+00:00",
            "train_end_date": "2019-01-08T00:00:00+00:00",
        },
        "model": {
            "gordo_tpu.models.models.TransformerAutoEncoder": {
                "kind": "transformer_model",
                "lookback_window": 16,
                "d_model": 32,
                "num_heads": 8,
                "ff_dim": 64,
                "tensor_parallel": 8,
            }
        },
    }
    machine = Machine.from_config(config, project_name="tp-test")
    assert _plan_machine(machine) is None  # serial path owns TP machines


def test_tp_predict_skips_serving_batcher(monkeypatch):
    from gordo_tpu.server import batcher as batcher_mod

    model = _fit(8)
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    calls = []
    monkeypatch.setattr(
        batcher_mod.CrossModelBatcher,
        "submit",
        lambda self, *a: calls.append(a),
    )
    out = model.predict(_data())
    assert calls == []  # went direct, not through the batcher
    assert out.shape[1] == N_TAGS


def test_tp_artifact_roundtrip(tmp_path):
    """Sharded params must gather into a portable artifact and load back."""
    import pickle

    model = _fit(8)
    blob = pickle.dumps(model)
    loaded = pickle.loads(blob)
    # unpickled params are host numpy...
    assert isinstance(
        jax.tree_util.tree_leaves(loaded.params_)[0], np.ndarray
    )
    out = loaded.predict(_data())
    # ...and the first predict re-establishes the model-mesh sharding, so
    # the artifact keeps TP's capacity property when served
    wq = loaded.params_[2]["wq"]
    assert len(wq.sharding.device_set) == 8
    np.testing.assert_allclose(
        model.predict(_data()), out, rtol=2e-4, atol=2e-5
    )


def test_tp_never_runs_fused_qkv_even_from_old_artifacts():
    """The fused QKV projection concatenates column-sharded weights, which
    costs all-gathers/all-to-alls under the Megatron layout. The guard is
    structural (apply_model decides at the point of use), so even an
    artifact pickled before the fuse_qkv field existed — whose blocks
    default the flag ON — compiles to the clean comm pattern."""
    import dataclasses
    import pickle

    import jax

    from gordo_tpu.models.models import TransformerAutoEncoder
    from gordo_tpu.models.spec import TransformerBlock
    from gordo_tpu.ops.nn import apply_model

    est = TransformerAutoEncoder(
        kind="transformer_model", lookback_window=16, num_heads=8,
        tensor_parallel=8, epochs=1, batch_size=16,
    )
    X = np.random.RandomState(0).rand(64, 8).astype(np.float32)
    est.fit(X, X)
    # simulate a pre-field artifact: force fuse_qkv back on, round-trip
    est.spec_ = dataclasses.replace(
        est.spec_,
        layers=tuple(
            dataclasses.replace(l, fuse_qkv=True)
            if isinstance(l, TransformerBlock) else l
            for l in est.spec_.layers
        ),
    )
    loaded = pickle.loads(pickle.dumps(est))
    assert loaded.predict(X).shape[0] > 0
    # the compiled forward over the resharded params has no concat-induced
    # resharding collectives (the fused path measurably introduces them)
    xb = jnp.asarray(X[:16])[:, None, :].repeat(16, axis=1)
    txt = (
        jax.jit(lambda p, x: apply_model(loaded.spec_, p, x)[0])
        .lower(loaded.params_, xb)
        .compile()
        .as_text()
    )
    assert "all-to-all" not in txt
    assert "all-gather" not in txt
