"""The fleet observability plane (ISSUE 9): telemetry shards + merge
semantics (observability/shared.py), per-model SLO windows and burn rates
(observability/slo.py), and the device telemetry sampler
(observability/device.py)."""

import json
import os

import pytest

from gordo_tpu.observability import device, shared, slo, telemetry


@pytest.fixture(autouse=True)
def _clean_plane(tmp_path, monkeypatch):
    monkeypatch.setenv(shared.ENV_DIR, str(tmp_path))
    shared.reset_for_tests()
    slo.reset()
    device.reset_for_tests()
    yield
    shared.reset_for_tests()
    slo.reset()
    device.reset_for_tests()


def _registry_with_traffic() -> telemetry.MetricsRegistry:
    registry = telemetry.MetricsRegistry()
    registry.counter("gordo_server_t_requests_total", "test requests").inc(3)
    registry.gauge("gordo_server_t_queue_depth", "test depth").set(2.0)
    registry.histogram(
        "gordo_server_t_latency_seconds", "test latency"
    ).observe(0.05)
    return registry


# -------------------------------------------------------------- shard I/O
def test_shard_write_read_roundtrip(tmp_path):
    registry = _registry_with_traffic()
    assert shared.flush(force=True, registry=registry)
    shards = shared.read_shards()
    assert len(shards) == 1
    assert shards[0]["pid"] == os.getpid()
    by_name = {m["name"]: m for m in shards[0]["metrics"]}
    assert by_name["gordo_server_t_requests_total"]["series"] == [[[], 3.0]]


def test_flush_throttles_between_forced_writes():
    registry = _registry_with_traffic()
    assert shared.flush(force=True, registry=registry)
    # within the flush interval an unforced flush is a no-op
    assert not shared.flush(registry=registry)
    assert shared.flush(force=True, registry=registry)


def test_flush_noop_without_dir(monkeypatch):
    monkeypatch.delenv(shared.ENV_DIR)
    assert not shared.flush(force=True)
    assert shared.render_fleet_text() is None
    assert shared.fleet_vars() is None


def test_torn_shard_is_skipped(tmp_path):
    # odd seqlock version = writer died mid-slot; the reader must skip it
    payload = json.dumps({"schema": shared.PAYLOAD_SCHEMA, "pid": 1}).encode()
    torn = shared._HEADER.pack(shared._MAGIC, 1, len(payload)) + payload
    with open(shared.shard_path(1), "wb") as fh:
        fh.write(torn)
    assert shared.read_shards() == []


def test_garbage_shard_is_skipped(tmp_path):
    with open(shared.shard_path(2), "wb") as fh:
        fh.write(b"not a shard at all")
    assert shared.read_shards() == []


def test_mark_shard_dead_removes_file():
    registry = _registry_with_traffic()
    shared.flush(force=True, registry=registry)
    path = shared.shard_path(os.getpid())
    assert os.path.exists(path)
    shared.mark_shard_dead(os.getpid())
    assert not os.path.exists(path)
    assert shared.read_shards() == []


# ---------------------------------------------------------------- merging
def _fake_shard(pid: int, metrics) -> dict:
    return {"schema": shared.PAYLOAD_SCHEMA, "pid": pid, "metrics": metrics}


def test_merge_counters_sum_across_workers():
    entry = {
        "name": "gordo_server_t_requests_total",
        "kind": "counter",
        "help": "h",
        "labelnames": ["endpoint"],
        "series": [[["/predict"], 5.0]],
    }
    families = shared.merge_shards(
        [_fake_shard(1, [entry]), _fake_shard(2, [entry])]
    )
    family = families["gordo_server_t_requests_total"]
    assert family["series"][("/predict",)] == 10.0


def test_merge_gauges_sum_by_default_max_for_ratios():
    def gauge(name, value):
        return {
            "name": name, "kind": "gauge", "help": "h",
            "labelnames": [], "series": [[[], value]],
        }

    ratio_name = "gordo_server_device_busy_ratio"
    assert ratio_name in shared.GAUGE_MAX_MERGE
    shards = [
        _fake_shard(1, [gauge("gordo_server_t_depth", 2.0),
                        gauge(ratio_name, 0.9)]),
        _fake_shard(2, [gauge("gordo_server_t_depth", 3.0),
                        gauge(ratio_name, 0.4)]),
    ]
    families = shared.merge_shards(shards)
    # additive gauge: fleet total is the sum
    assert families["gordo_server_t_depth"]["series"][()] == 5.0
    # ratio gauge: summing workers' duty cycles into 1.3 would be a lie
    assert families[ratio_name]["series"][()] == 0.9
    # per-worker series keep each worker's own value
    assert families[ratio_name]["per_worker"][("1",)] == 0.9
    assert families[ratio_name]["per_worker"][("2",)] == 0.4


def test_merge_histograms_elementwise():
    entry = {
        "name": "gordo_server_t_latency_seconds",
        "kind": "histogram",
        "help": "h",
        "labelnames": [],
        "buckets": [0.1, 1.0, "inf"],
        "series": [[[], [[1, 2, 0], 0.5]]],
    }
    families = shared.merge_shards(
        [_fake_shard(1, [entry]), _fake_shard(2, [entry])]
    )
    counts, total = families["gordo_server_t_latency_seconds"]["series"][()]
    assert counts == [2, 4, 0]
    assert total == 1.0


# -------------------------------------------------------------- rendering
def test_render_fleet_text_exposition():
    # render flushes the DEFAULT registry into this process's shard, so
    # the probe series must live there (unique names: the registry is a
    # process-global get-or-create)
    telemetry.counter("gordo_server_t_render_total", "probe").inc(3)
    telemetry.histogram("gordo_server_t_render_seconds", "probe").observe(
        0.05
    )
    text = shared.render_fleet_text()
    assert "gordo_server_fleet_workers 1" in text
    assert "# TYPE gordo_server_fleet_workers gauge" in text
    assert "gordo_server_t_render_total 3" in text
    # histogram exposition: cumulative buckets + sum + count
    assert 'gordo_server_t_render_seconds_bucket{le="+Inf"} 1' in text
    assert "gordo_server_t_render_seconds_count 1" in text


def test_fleet_vars_census_and_merge():
    telemetry.counter("gordo_server_t_vars_total", "probe").inc(7)
    fleet = shared.fleet_vars()
    assert fleet["workers"] == 1
    assert fleet["pids"] == [os.getpid()]
    merged = fleet["merged"]["gordo_server_t_vars_total"]
    assert merged["series"][""] == 7.0


def test_fleet_extras_roundtrip():
    shared.register_extra("blob", lambda: {"answer": 42})
    shared.flush(force=True, registry=telemetry.MetricsRegistry())
    extras = shared.fleet_extras("blob")
    assert extras == [(os.getpid(), {"answer": 42})]


def test_sampler_runs_before_flush():
    seen = []
    shared.register_sampler(lambda: seen.append(1))
    shared.flush(force=True, registry=telemetry.MetricsRegistry())
    assert seen == [1]


# -------------------------------------------------------------------- SLO
def test_slo_snapshot_and_burn_rates(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_SLO_P99_MS", "100")
    monkeypatch.setenv("GORDO_TPU_SLO_ERROR_BUDGET", "0.01")
    for _ in range(96):
        slo.record("model-a", 0.01, 200)
    for _ in range(2):
        slo.record("model-a", 0.5, 200)  # slow: > 100ms objective
    for _ in range(2):
        slo.record("model-a", 0.01, 500)  # errors
    snap = slo.snapshot()
    window = snap["models"]["model-a"]["5m"]
    assert window["requests"] == 100
    assert window["errors"] == 2
    assert window["slow"] == 2
    assert window["error_rate"] == pytest.approx(0.02)
    # 2% errors against a 1% budget: burning at 2x
    assert window["error_burn_rate"] == pytest.approx(2.0)
    assert window["latency_burn_rate"] == pytest.approx(2.0)
    assert window["p99_ms"] is not None
    # both windows exist and agree on totals at this timescale
    assert snap["models"]["model-a"]["1h"]["requests"] == 100


def test_slo_merge_payloads_is_exact(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_SLO_P99_MS", "100")
    for _ in range(10):
        slo.record("model-a", 0.01, 200)
    slo.record("model-a", 0.2, 500)
    payload = slo.shard_payload()
    # two identical workers: epoch-aligned sub-windows merge by summing
    fleet = slo.merge_payloads([(1, payload), (2, payload)])
    window = fleet["models"]["model-a"]["5m"]
    assert fleet["workers"] == 2
    assert window["requests"] == 22
    assert window["errors"] == 2
    local = slo.snapshot()["models"]["model-a"]["5m"]
    assert window["error_rate"] == pytest.approx(local["error_rate"])


def test_slo_merge_tolerates_garbage_payloads():
    fleet = slo.merge_payloads(
        [(1, "not a dict"), (2, {"m": {"5m": [["bad row"]]}})]
    )
    assert fleet["models"].get("m", {}).get("5m", {}).get("requests", 0) == 0


def test_slo_refresh_gauges_exports_series():
    from gordo_tpu.observability import metrics as metric_catalog

    slo.record("model-b", 0.01, 200)
    slo.refresh_gauges()
    series = dict(metric_catalog.SLO_REQUESTS.snapshot())
    assert series[("model-b", "5m")] >= 1


def test_slo_empty_model_name_ignored():
    slo.record("", 0.01, 200)
    assert slo.snapshot()["models"] == {}


def test_slo_rides_the_shard(monkeypatch):
    slo.install_shard_hooks()
    slo.record("model-c", 0.02, 200)
    shared.flush(force=True, registry=telemetry.MetricsRegistry())
    extras = shared.fleet_extras("slo")
    assert len(extras) == 1
    _pid, payload = extras[0]
    assert "model-c" in payload
    fleet = slo.merge_payloads(extras)
    assert fleet["models"]["model-c"]["5m"]["requests"] == 1


# ----------------------------------------------------------------- device
def test_device_sample_and_snapshot():
    # no batcher, CPU backend: everything must still be best-effort green
    device.sample()
    snap = device.snapshot()
    assert set(snap) >= {
        "busy_ratio", "busy_seconds_total", "achieved_flops_total",
        "online_mfu", "peak_flops", "peak_source", "param_bank_bytes",
        "param_bank_occupancy", "program_cache_entries",
    }
    assert snap["peak_source"] in ("env", "table", "measured")
    assert snap["peak_flops"] is None or snap["peak_flops"] >= 0


def test_device_busy_ratio_clamped(monkeypatch):
    from gordo_tpu.observability import metrics as metric_catalog

    device.reset_for_tests()
    device.sample()  # establishes the baseline sample
    # an absurd busy-seconds jump must clamp the duty cycle at 1.0
    metric_catalog.DEVICE_BUSY_SECONDS.inc(1e6)
    import time

    time.sleep(0.02)  # past the scrape-storm guard interval
    device.sample()
    assert metric_catalog.DEVICE_BUSY_RATIO.value() <= 1.0


def test_device_hooks_register_sampler():
    device.install_shard_hooks()
    shared.flush(force=True, registry=telemetry.MetricsRegistry())
    from gordo_tpu.observability import metrics as metric_catalog

    # the flush ran the sampler: program-cache gauge has a real value
    assert metric_catalog.PROGRAM_CACHE_ENTRIES.value() >= 0
