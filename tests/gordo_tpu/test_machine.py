import pytest

from gordo_tpu.machine import Machine
from gordo_tpu.machine.validators import ValidUrlString, fix_resource_limits
from gordo_tpu.workflow.helpers import patch_dict
from gordo_tpu.workflow.normalized_config import NormalizedConfig


def base_config(name="machine-1"):
    return {
        "name": name,
        "dataset": {
            "type": "RandomDataset",
            "train_start_date": "2019-01-01T00:00:00+00:00",
            "train_end_date": "2019-01-02T00:00:00+00:00",
            "tags": ["tag-0"],
        },
        "model": {
            "gordo_tpu.models.models.AutoEncoder": {"kind": "feedforward_hourglass"}
        },
    }


def test_machine_from_config_roundtrip():
    machine = Machine.from_config(base_config(), project_name="proj")
    d = machine.to_dict()
    machine2 = Machine.from_dict(d)
    assert machine == machine2
    assert machine.host == "gordoserver-proj-machine-1"


def test_invalid_name_rejected():
    cfg = base_config(name="Invalid_Name")
    with pytest.raises(ValueError):
        Machine.from_config(cfg, project_name="proj")


def test_invalid_model_rejected():
    cfg = base_config()
    cfg["model"] = {"not.a.real.Thing": {}}
    with pytest.raises(ValueError):
        Machine.from_config(cfg, project_name="proj")


def test_globals_patching():
    cfg = base_config()
    config_globals = {
        "evaluation": {"cv_mode": "cross_val_only"},
        "runtime": {"builder": {"resources": {"requests": {"memory": 100}}}},
        "metadata": {"source": "global"},
    }
    machine = Machine.from_config(cfg, "proj", config_globals=config_globals)
    assert machine.evaluation["cv_mode"] == "cross_val_only"
    assert machine.metadata.user_defined["global-metadata"] == {"source": "global"}
    # machine-level evaluation overrides globals
    cfg2 = base_config()
    cfg2["evaluation"] = {"cv_mode": "full_build"}
    machine2 = Machine.from_config(cfg2, "proj", config_globals=config_globals)
    assert machine2.evaluation["cv_mode"] == "full_build"


def test_valid_url_string():
    assert ValidUrlString.valid_url_string("abc-123")
    assert not ValidUrlString.valid_url_string("Abc")
    assert not ValidUrlString.valid_url_string("a" * 64)
    assert not ValidUrlString.valid_url_string("-abc")


def test_fix_resource_limits():
    fixed = fix_resource_limits(
        {"requests": {"memory": 10}, "limits": {"memory": 5}}
    )
    assert fixed["requests"]["memory"] == 5
    fixed2 = fix_resource_limits({"requests": {"cpu": 1}, "limits": {"cpu": 4}})
    assert fixed2["requests"]["cpu"] == 1


def test_patch_dict_does_not_mutate():
    original = {"a": {"b": 1}}
    patched = patch_dict(original, {"a": {"c": 2}})
    assert original == {"a": {"b": 1}}
    assert patched == {"a": {"b": 1, "c": 2}}


def test_normalized_config_defaults(config_str):
    import yaml

    config = yaml.safe_load(config_str)
    norm = NormalizedConfig(config, project_name="proj")
    assert len(norm.machines) == 2
    machine = norm.machines[0]
    assert machine.evaluation["cv_mode"] == "full_build"
    assert machine.evaluation["scoring_scaler"] == "sklearn.preprocessing.MinMaxScaler"
    assert "builder" in machine.runtime
