"""Persistent XLA compile-cache keying (round-4 verdict item 3): the cache
dir must be partitioned by host machine features, not just platform tag, so
AOT artifacts from another host are never offered to this one."""

import os
from unittest import mock

import jax
import pytest

from gordo_tpu.util.xla_cache import host_fingerprint, setup_persistent_xla_cache


@pytest.fixture(autouse=True)
def _restore_jax_cache_config():
    prior = jax.config.jax_compilation_cache_dir
    yield
    jax.config.update("jax_compilation_cache_dir", prior)


def test_fingerprint_stable_and_short():
    a, b = host_fingerprint(), host_fingerprint()
    assert a == b
    assert len(a) == 12
    int(a, 16)  # hex


def test_cache_dir_includes_platform_and_fingerprint():
    with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "cpu"}, clear=False):
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        cache_dir = setup_persistent_xla_cache()
    assert cache_dir == f"/tmp/gordo_tpu_xla_cache-cpu-{host_fingerprint()}"


def test_explicit_env_dir_wins():
    with mock.patch.dict(
        os.environ, {"JAX_COMPILATION_CACHE_DIR": "/tmp/explicit-cache"}
    ):
        assert setup_persistent_xla_cache() == "/tmp/explicit-cache"
