"""Persistent XLA compile-cache keying (round-4 verdict item 3): the cache
dir must be partitioned by host machine features, not just platform tag, so
AOT artifacts from another host are never offered to this one. Plus the
cosmetic AOT-warning filter (ISSUE 9): the known-harmless
``+prefer-no-gather``/``+prefer-no-scatter`` mismatch is silenced at the
logging layer, while any genuine ISA mismatch still warns."""

import logging
import os
from unittest import mock

import jax
import pytest

from gordo_tpu.util.xla_cache import (
    CosmeticAotMismatchFilter,
    host_fingerprint,
    install_aot_warning_filter,
    is_cosmetic_aot_mismatch,
    setup_persistent_xla_cache,
)


@pytest.fixture(autouse=True)
def _restore_jax_cache_config():
    prior = jax.config.jax_compilation_cache_dir
    yield
    jax.config.update("jax_compilation_cache_dir", prior)


def test_fingerprint_stable_and_short():
    a, b = host_fingerprint(), host_fingerprint()
    assert a == b
    assert len(a) == 12
    int(a, 16)  # hex


def test_cache_dir_includes_platform_and_fingerprint():
    with mock.patch.dict(os.environ, {"JAX_PLATFORMS": "cpu"}, clear=False):
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        cache_dir = setup_persistent_xla_cache()
    assert cache_dir == f"/tmp/gordo_tpu_xla_cache-cpu-{host_fingerprint()}"


def test_explicit_env_dir_wins():
    with mock.patch.dict(
        os.environ, {"JAX_COMPILATION_CACHE_DIR": "/tmp/explicit-cache"}
    ):
        assert setup_persistent_xla_cache() == "/tmp/explicit-cache"


# ------------------------------------------- cosmetic AOT-warning filter
_COSMETIC_MSG = (
    "The loaded executable was compiled with CPU features "
    "'+avx2,+fma,+prefer-no-gather,+prefer-no-scatter' but the host "
    "supports '+avx2,+fma'; this discrepancy could lead to execution "
    "errors such as SIGILL."
)
_GENUINE_MSG = (
    "The loaded executable was compiled with CPU features "
    "'+avx2,+avx512f,+prefer-no-gather' but the host supports "
    "'+avx2,+prefer-no-gather'; this discrepancy could lead to execution "
    "errors such as SIGILL."
)


def _warning_record(message: str) -> logging.LogRecord:
    return logging.LogRecord(
        "jax._src.compiler", logging.WARNING, __file__, 1, message, None, None
    )


def test_cosmetic_mismatch_detected():
    assert is_cosmetic_aot_mismatch(_COSMETIC_MSG)


def test_genuine_isa_mismatch_stays_loud():
    # one differing feature is real (avx512f): must NOT be classified
    # cosmetic even though a cosmetic pseudo-feature appears in both lists
    assert not is_cosmetic_aot_mismatch(_GENUINE_MSG)
    assert CosmeticAotMismatchFilter().filter(_warning_record(_GENUINE_MSG))


def test_filter_drops_only_the_cosmetic_warning():
    flt = CosmeticAotMismatchFilter()
    assert not flt.filter(_warning_record(_COSMETIC_MSG))
    assert flt.filter(_warning_record("unrelated warning about SIGILL"))
    assert flt.filter(_warning_record("ordinary log line"))


def test_unparseable_feature_lists_stay_loud():
    # parse failure must never silence: no quoted feature lists here
    message = "execution errors such as SIGILL may occur"
    assert not is_cosmetic_aot_mismatch(message)


def test_identical_feature_lists_not_classified_cosmetic():
    # empty symmetric diff means this is not the mismatch warning shape
    message = (
        "features '+avx2,+prefer-no-gather' vs '+avx2,+prefer-no-gather' "
        "could lead to execution errors such as SIGILL"
    )
    assert not is_cosmetic_aot_mismatch(message)


def test_install_is_idempotent_and_attached():
    install_aot_warning_filter()
    install_aot_warning_filter()
    jax_logger = logging.getLogger("jax._src.compiler")
    cosmetic_filters = [
        f for f in jax_logger.filters
        if isinstance(f, CosmeticAotMismatchFilter)
    ]
    assert len(cosmetic_filters) == 1


def test_setup_installs_the_filter():
    with mock.patch.dict(os.environ, {}, clear=False):
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        setup_persistent_xla_cache()
    assert any(
        isinstance(f, CosmeticAotMismatchFilter)
        for f in logging.getLogger("jax").filters
    )
