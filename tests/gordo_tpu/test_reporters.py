"""
Reporter tests.

Mirrors the reference strategy: postgres exercised against a real DB-API
connection (sqlite3 stands in for the dockerized postgres 11 the reference
uses, tests/conftest.py:270-332); mlflow batching logic tested pure
(reference tests/gordo/reporters/test_mlflow.py).
"""

import sqlite3

import pytest

from gordo_tpu.machine import Machine
from gordo_tpu.reporters.base import BaseReporter
from gordo_tpu.reporters.mlflow import (
    MAX_METRICS_PER_BATCH,
    MAX_PARAMS_PER_BATCH,
    MlFlowReporter,
    MlFlowReporterException,
    batch,
    extract_metrics_and_params,
    get_batch_kwargs,
)
from gordo_tpu.reporters.postgres import (
    PostgresReporter,
    PostgresReporterException,
)


@pytest.fixture
def machine():
    return Machine.from_config(
        {
            "name": "report-machine",
            "dataset": {
                "type": "RandomDataset",
                "tags": ["tag-1", "tag-2"],
                "train_start_date": "2019-01-01T00:00:00+00:00",
                "train_end_date": "2019-01-02T00:00:00+00:00",
            },
            "model": {
                "gordo_tpu.models.models.AutoEncoder": {
                    "kind": "feedforward_hourglass"
                }
            },
        },
        project_name="test-proj",
    )


@pytest.fixture
def sqlite_factory(tmp_path):
    db = str(tmp_path / "reporter.db")

    def connect():
        return sqlite3.connect(db)

    return connect


def test_postgres_reporter_upserts(machine, sqlite_factory):
    reporter = PostgresReporter(
        connection_factory=sqlite_factory, paramstyle="?"
    )
    reporter.report(machine)
    reporter.report(machine)  # second report upserts, not duplicates

    conn = sqlite_factory()
    rows = conn.execute("SELECT name, model FROM machine").fetchall()
    assert len(rows) == 1
    assert rows[0][0] == "report-machine"
    assert "AutoEncoder" in rows[0][1]
    conn.close()


def test_postgres_reporter_requires_target():
    with pytest.raises(ValueError):
        PostgresReporter()


def test_postgres_reporter_connect_failure(machine):
    def broken():
        raise OSError("no route to host")

    reporter = PostgresReporter(connection_factory=broken)
    with pytest.raises(PostgresReporterException):
        reporter.report(machine)


def test_postgres_reporter_from_runtime_config(machine, sqlite_factory):
    """Reporters declared in runtime config resolve through the serializer."""
    reporter = BaseReporter.from_dict(
        {
            "gordo_tpu.reporters.postgres.PostgresReporter": {
                "host": "example.com"
            }
        }
    )
    assert isinstance(reporter, PostgresReporter)
    # reference-path alias too
    reporter = BaseReporter.from_dict(
        {"gordo.reporters.postgres.PostgresReporter": {"host": "example.com"}}
    )
    assert isinstance(reporter, PostgresReporter)


def test_machine_report_dispatch(machine, sqlite_factory, monkeypatch):
    """Machine.report() runs every reporter in runtime.reporters."""

    seen = []
    monkeypatch.setattr(
        PostgresReporter, "report", lambda self, m: seen.append(m.name)
    )
    machine.runtime["reporters"] = [
        {
            "gordo_tpu.reporters.postgres.PostgresReporter": {
                "host": "example.com"
            }
        }
    ]
    machine.report()
    assert seen == ["report-machine"]


def _machine_dict_with_scores(n_metrics=2, n_epochs=3):
    scores = {
        f"metric-{i}": {"mean": 0.5, "std": 0.1, "max": 0.9, "min": 0.2}
        for i in range(n_metrics)
    }
    return {
        "metadata": {
            "build_metadata": {
                "model": {
                    "cross_validation": {
                        "scores": scores,
                        "cv_duration_sec": 12.5,
                    },
                    # the REAL builder shape: the estimator's get_metadata
                    # dict (with its history) nests under model_meta
                    # (machine/metadata.py ModelBuildMetadata.model_meta)
                    "model_meta": {
                        "history": {
                            "loss": [float(i) for i in range(n_epochs)]
                        }
                    },
                    "model_training_duration_sec": 3.2,
                }
            }
        }
    }


def test_extract_metrics_and_params():
    metrics, params = extract_metrics_and_params(_machine_dict_with_scores())
    metric_keys = {k for k, _ in metrics}
    assert "metric-0-mean" in metric_keys
    assert "history-loss-epoch-2" in metric_keys
    param_keys = {k for k, _ in params}
    assert {"cv_duration_sec", "model_training_duration_sec"} <= param_keys


def test_batching_respects_limits():
    assert batch(list(range(5)), 2) == [[0, 1], [2, 3], [4]]
    with pytest.raises(ValueError):
        batch([1], 0)
    # 80 metrics/metric-stats * 4 + 60 epochs > 200 -> multiple batches
    machine_dict = _machine_dict_with_scores(n_metrics=80, n_epochs=60)
    calls = get_batch_kwargs(machine_dict)
    assert len(calls) >= 2
    for call in calls:
        assert len(call["metrics"]) <= MAX_METRICS_PER_BATCH
        assert len(call["params"]) <= MAX_PARAMS_PER_BATCH


def test_mlflow_reporter_missing_dependency(machine):
    reporter = MlFlowReporter()
    with pytest.raises(MlFlowReporterException):
        reporter.report(machine)


def test_extract_history_from_real_build():
    """Pin the extract against a REAL builder-produced machine dict — a
    hand-built fixture once drifted from the builder's actual shape and
    silently dropped every history metric."""
    from gordo_tpu.builder.build_model import ModelBuilder
    from gordo_tpu.machine import Machine

    machine = Machine.from_config(
        {
            "name": "mlflow-hist",
            "dataset": {
                "type": "RandomDataset",
                "tags": ["h-0", "h-1"],
                "train_start_date": "2019-01-01T00:00:00+00:00",
                "train_end_date": "2019-01-02T00:00:00+00:00",
            },
            "model": {
                "gordo_tpu.models.models.AutoEncoder": {
                    "kind": "feedforward_hourglass",
                    "epochs": 2,
                }
            },
        },
        project_name="mlflow-test",
    )
    _, machine_out = ModelBuilder(machine).build()
    metrics, _ = extract_metrics_and_params(machine_out.to_dict())
    keys = {k for k, _ in metrics}
    assert any(k.startswith("history-loss-epoch-") for k in keys), keys
