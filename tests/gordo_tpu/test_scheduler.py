"""
Elastic fleet-build scheduler (parallel/scheduler.py) + the elastic build
path of BatchedModelBuilder.

Three layers of coverage:

1. pure lease-protocol unit tests (no jax work): exactly-once acquisition,
   steal-after-expiry with generation fencing, static-policy share
   restriction, compile-affinity placement, exactly-once claims;
2. in-process single-host elastic builds: full build, cache rerun with
   zero retrains, warm-start delta rebuild retraining exactly the one
   drifted machine;
3. the 2-process chaos test: a host killed mid-build via the
   ``scheduler_lease``/``die`` fault rule, the survivor steals its stale
   lease, and the finished artifact set is byte-identical to a plain
   single-host build of the same fleet.
"""

import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time
import zlib

import pytest
import yaml

from gordo_tpu.parallel.scheduler import (
    ElasticScheduler,
    WorkUnit,
    scheduler_dir_for,
    unit_id_for,
)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _sched(tmp_path, rank, num_hosts=2, **kw):
    kw.setdefault("lease_timeout_s", 30.0)
    kw.setdefault("heartbeat_s", 5.0)
    return ElasticScheduler(
        str(tmp_path),
        host_id=f"host-{rank}",
        host_rank=rank,
        num_hosts=num_hosts,
        **kw,
    )


def _unit(name, **kw):
    return WorkUnit(unit_id_for([name]), (name,), **kw)


# ------------------------------------------------------------ lease protocol
def test_unit_id_stable_and_member_order_independent():
    assert unit_id_for(["b", "a"]) == unit_id_for(["a", "b"])
    assert unit_id_for(["a"]) != unit_id_for(["b"])
    assert unit_id_for(["a"], "serial") != unit_id_for(["a"], "bucket")
    assert unit_id_for(["a"], "serial").startswith("serial-")


def test_two_hosts_drain_queue_without_overlap(tmp_path):
    units = {}
    for i in range(6):
        u = _unit(f"part-m{i}", cost=i + 1)
        units[u.unit_id] = u
    h0, h1 = _sched(tmp_path, 0), _sched(tmp_path, 1)
    taken = {0: [], 1: []}
    pending = {0: True, 1: True}
    while any(pending.values()):
        for rank, h in ((0, h0), (1, h1)):
            if not pending[rank]:
                continue
            lease = h.next_lease(units, poll_s=0.01)
            if lease is None:
                pending[rank] = False
                continue
            taken[rank].append(lease.unit.unit_id)
            h.mark_done(lease, {"built": lease.unit.cost})
    h0.close(), h1.close()

    # every unit done exactly once, each by exactly one host
    assert sorted(taken[0] + taken[1]) == sorted(units)
    assert not (set(taken[0]) & set(taken[1]))
    ledger = h0.summary()
    assert sorted(e["unit"] for e in ledger) == sorted(units)
    for entry in ledger:
        assert entry["host"] in ("host-0", "host-1")
        assert entry["kind"] == "bucket"
    # steal accounting is by nominal share: every lease is either fresh
    # (own share) or a steal (peer's share drained early) and they add up
    for rank, h in ((0, h0), (1, h1)):
        assert h.stats["leases_fresh"] + h.stats["leases_steal"] == len(
            taken[rank]
        )
    # nobody expired — these were drain-steals, not dead-host takeovers
    assert h0.stats["lease_expirations"] == 0
    assert h1.stats["lease_expirations"] == 0


def test_try_claim_is_exactly_once(tmp_path):
    h0, h1 = _sched(tmp_path, 0), _sched(tmp_path, 1)
    uid = unit_id_for(["cache-m0"], "cached")
    assert h0.try_claim(uid, {"machine": "cache-m0"}) is True
    assert h1.try_claim(uid, {"machine": "cache-m0"}) is False
    assert h0.is_done(uid) and h1.is_done(uid)
    assert h0.stats["claims"] == 1 and h1.stats["claims"] == 0
    (entry,) = h0.summary()
    assert entry["machine"] == "cache-m0" and entry["host"] == "host-0"
    h0.close(), h1.close()


def test_expired_lease_is_stolen_and_old_holder_fenced(tmp_path):
    u = _unit("steal-m0")
    units = {u.unit_id: u}
    h0 = _sched(tmp_path, 0, lease_timeout_s=0.3, heartbeat_s=30.0)
    l0 = h0.next_lease(units, poll_s=0.01)
    assert l0 is not None and l0.generation == 1 and not l0.stolen
    h0.close()  # heartbeat stops; the lease goes stale
    time.sleep(0.5)

    h1 = _sched(tmp_path, 1, lease_timeout_s=0.3, heartbeat_s=30.0)
    l1 = h1.next_lease(units, poll_s=0.01)
    assert l1 is not None and l1.stolen and l1.generation == 2
    assert h1.stats["lease_expirations"] == 1
    assert h1.stats["leases_steal"] == 1
    # generation fencing: the original holder must discard its result
    assert not h0.still_current(l0)
    assert h1.still_current(l1)
    h1.mark_done(l1)
    assert h0.next_lease(units, poll_s=0.01) is None
    h1.close()


def test_heartbeat_keeps_a_slow_build_leased(tmp_path):
    u = _unit("slow-m0")
    units = {u.unit_id: u}
    h0 = _sched(tmp_path, 0, lease_timeout_s=0.4, heartbeat_s=0.1)
    l0 = h0.next_lease(units, poll_s=0.01)
    time.sleep(0.8)  # two timeouts pass, but the heartbeat refreshes mtime
    h1 = _sched(tmp_path, 1, lease_timeout_s=0.4, heartbeat_s=0.1)
    # nothing stealable and nothing unleased: the peer sees no candidate
    start = time.time()
    got = []
    while time.time() - start < 0.5 and not got:
        cur = h1._current_lease(u.unit_id)
        assert cur is not None
        gen, _, age = cur
        if age > h1.lease_timeout_s:
            got.append(gen)
        time.sleep(0.05)
    assert not got, "heartbeated lease went stale"
    assert h0.still_current(l0)
    h0.mark_done(l0)
    h0.close(), h1.close()


def _units_by_owner(num_hosts=2, per_owner=2):
    units, by_owner = {}, {r: [] for r in range(num_hosts)}
    i = 0
    while any(len(v) < per_owner for v in by_owner.values()):
        uid = unit_id_for([f"share-m{i}"])
        owner = zlib.crc32(uid.encode()) % num_hosts
        if len(by_owner[owner]) < per_owner:
            units[uid] = WorkUnit(uid, (f"share-m{i}",))
            by_owner[owner].append(uid)
        i += 1
    return units, by_owner


def test_static_policy_never_touches_peer_share(tmp_path):
    units, by_owner = _units_by_owner()
    h0 = _sched(tmp_path, 0, policy="static")
    drained = []
    while True:
        lease = h0.next_lease(units, poll_s=0.01)
        if lease is None:
            break
        drained.append(lease.unit.unit_id)
        h0.mark_done(lease)
    h0.close()
    # own share fully built; peer share untouched AND not waited on
    assert sorted(drained) == sorted(by_owner[0])
    assert h0.stats["leases_steal"] == 0
    for uid in by_owner[1]:
        assert not h0.is_done(uid)


def test_static_policy_releases_its_own_ghost_lease(tmp_path):
    """A crashed prior attempt of the SAME host leaves a stale lease on its
    own share; static mode must re-lease it rather than deadlock."""
    units, by_owner = _units_by_owner(per_owner=1)
    uid = by_owner[0][0]
    ghost = _sched(tmp_path, 0, policy="static", lease_timeout_s=0.3,
                   heartbeat_s=30.0)
    l_ghost = ghost.next_lease(units, poll_s=0.01)
    assert l_ghost.unit.unit_id == uid
    ghost.close()  # crash stand-in: lease never marked done
    time.sleep(0.5)

    again = _sched(tmp_path, 0, policy="static", lease_timeout_s=0.3,
                   heartbeat_s=30.0)
    lease = again.next_lease(units, poll_s=0.01)
    assert lease is not None and lease.unit.unit_id == uid
    assert lease.generation == 2
    # re-leasing your own ghost is not a steal and not a peer expiry
    assert not lease.stolen
    assert again.stats["lease_expirations"] == 0
    assert again.stats["leases_steal"] == 0
    again.mark_done(lease)
    again.close()


def test_placement_prefers_compiled_signature_then_lpt(tmp_path):
    big = WorkUnit(unit_id_for(["lpt-big"]), ("lpt-big",),
                   signature="SIG-COLD", cost=8)
    small = WorkUnit(unit_id_for(["lpt-small"]), ("lpt-small",),
                     signature="SIG-WARM", cost=1)
    units = {big.unit_id: big, small.unit_id: small}

    # cold host: LPT — biggest unit first
    cold = _sched(tmp_path / "cold", 0, num_hosts=1)
    lease = cold.next_lease(units, poll_s=0.01)
    assert lease.unit.unit_id == big.unit_id
    cold.mark_done(lease)
    cold.close()

    # host that already compiled the small unit's signature takes it first
    # even though the big unit wins on LPT
    warm = _sched(tmp_path / "warm", 0, num_hosts=1)
    warm.note_compiled("SIG-WARM")
    lease = warm.next_lease(units, poll_s=0.01)
    assert lease.unit.unit_id == small.unit_id
    warm.mark_done(lease)
    warm.close()


def test_scheduler_dir_for_env_override(tmp_path, monkeypatch):
    monkeypatch.delenv("GORDO_TPU_SCHEDULER_DIR", raising=False)
    assert scheduler_dir_for("/out") == "/out/_scheduler"
    monkeypatch.setenv("GORDO_TPU_SCHEDULER_DIR", str(tmp_path))
    assert scheduler_dir_for("/out") == str(tmp_path)


def test_rejects_unknown_policy(tmp_path):
    with pytest.raises(ValueError):
        ElasticScheduler(str(tmp_path), policy="chaotic")


# ------------------------------------------------- in-process elastic builds
def _machine_config(name, end="2019-01-03T00:00:00+00:00"):
    return {
        "name": name,
        "dataset": {
            "type": "RandomDataset",
            "train_start_date": "2019-01-01T00:00:00+00:00",
            "train_end_date": end,
            "tags": [f"{name}-tag-a", f"{name}-tag-b"],
        },
        "model": {
            "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "gordo_tpu.models.models.AutoEncoder": {
                        "kind": "feedforward_hourglass",
                        "epochs": 1,
                    }
                }
            }
        },
    }


def _machines(names, **overrides):
    from gordo_tpu.machine import Machine

    return [
        Machine.from_config(
            _machine_config(n, **overrides.get(n, {})), project_name="elastic-test"
        )
        for n in names
    ]


def test_elastic_build_requires_shared_state():
    from gordo_tpu.parallel import BatchedModelBuilder

    builder = BatchedModelBuilder(
        _machines(["es-m0"]), elastic=True, warm_start=False
    )
    with pytest.raises(ValueError, match="shared state"):
        builder.build()


def test_elastic_build_cache_rerun_and_warm_start_delta(tmp_path):
    """The three-run acceptance cycle on one host:

    1. cold elastic build of 3 machines — every unit leased and done;
    2. rerun of the unchanged fleet — 0 retrained, all 3 returned from
       exactly-once cache claims, no leases taken;
    3. one machine's data window perturbed — exactly 1 machine retrains,
       and it warm-starts from the prior artifact's params.
    """
    from gordo_tpu.observability import metrics as mc
    from gordo_tpu.parallel import BatchedModelBuilder

    names = ["el-m0", "el-m1", "el-m2"]
    reg = str(tmp_path / "registry")

    out1 = str(tmp_path / "run1")
    b1 = BatchedModelBuilder(
        _machines(names), output_dir=out1, model_register_dir=reg,
        elastic=True, host_rank=0, num_hosts=1,
    )
    r1 = b1.build()
    assert sorted(m.name for _, m in r1) == names
    assert b1.scheduler is not None
    s1 = b1.scheduler.stats
    assert s1["leases_fresh"] + s1["leases_steal"] >= 1
    assert s1["lease_expirations"] == 0
    done_dir = os.path.join(out1, "_scheduler", "done")
    assert any(n.endswith(".json") for n in os.listdir(done_dir))
    for n in names:
        assert os.path.exists(os.path.join(out1, n, "model.pkl"))

    # unchanged rerun (fresh output_dir, shared registry): retrains 0
    out2 = str(tmp_path / "run2")
    b2 = BatchedModelBuilder(
        _machines(names), output_dir=out2, model_register_dir=reg,
        elastic=True, host_rank=0, num_hosts=1,
    )
    r2 = b2.build()
    assert sorted(m.name for _, m in r2) == names
    s2 = b2.scheduler.stats
    assert s2["claims"] == 3  # every machine returned via a cache claim
    assert s2["leases_fresh"] + s2["leases_steal"] == 0  # nothing retrained

    # perturb ONE machine's data window: full cache key misses, warm key
    # (data excluded) hits — exactly one retrain, warm-started
    warm_before = mc.WARM_STARTS.value()
    out3 = str(tmp_path / "run3")
    b3 = BatchedModelBuilder(
        _machines(names, **{"el-m0": {"end": "2019-01-04T00:00:00+00:00"}}),
        output_dir=out3, model_register_dir=reg,
        elastic=True, host_rank=0, num_hosts=1,
    )
    r3 = b3.build()
    assert sorted(m.name for _, m in r3) == names
    s3 = b3.scheduler.stats
    assert s3["claims"] == 2  # the two unchanged machines
    assert s3["leases_fresh"] + s3["leases_steal"] == 1  # one rebuilt unit
    assert mc.WARM_STARTS.value() - warm_before == 1
    assert os.path.exists(os.path.join(out3, "el-m0", "model.pkl"))


# ------------------------------------------------------ 2-process chaos test
N_CHAOS = 4

CHAOS_CONFIG = {
    "machines": [
        {
            "name": f"chaos-m{i}",
            "dataset": {
                "type": "RandomDataset",
                "train_start_date": "2019-01-01T00:00:00+00:00",
                # two distinct windows -> two row counts -> two buckets,
                # so there is a unit left to steal after the victim dies
                "train_end_date": (
                    "2019-01-02T00:00:00+00:00"
                    if i < 2
                    else "2019-01-03T00:00:00+00:00"
                ),
                "tags": [f"chaos-{i}-a", f"chaos-{i}-b"],
            },
            "model": {
                "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
                    "base_estimator": {
                        "gordo_tpu.models.models.AutoEncoder": {
                            "kind": "feedforward_hourglass",
                            "epochs": 1,
                        }
                    }
                }
            },
        }
        for i in range(N_CHAOS)
    ]
}

CHAOS_WORKER = """
import json, os, sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})

import yaml
from gordo_tpu.machine import Machine
from gordo_tpu.parallel import BatchedModelBuilder

rank = int(sys.argv[1])
outdir = sys.argv[2]
mode = sys.argv[3]  # "elastic" | "single"

with open(os.path.join(outdir, "config.yaml")) as f:
    config = yaml.safe_load(f)
machines = [
    Machine.from_config(c, project_name="chaos") for c in config["machines"]
]

kw = dict(
    output_dir=os.path.join(outdir, "models"),
    model_register_dir=os.path.join(outdir, "registry"),
    warm_start=False,
)
if mode == "elastic":
    kw.update(elastic=True, host_rank=rank, num_hosts=2)
builder = BatchedModelBuilder(machines, **kw)
results = builder.build()
stats = dict(builder.scheduler.stats) if builder.scheduler else {{}}
print("STATS " + json.dumps({{
    "rank": rank,
    "built": sorted(m.name for _, m in results),
    "stats": stats,
}}), flush=True)
"""


def _sha256(path):
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _spawn_chaos_worker(worker_py, rank, outdir, mode, env):
    return subprocess.Popen(
        [sys.executable, worker_py, str(rank), outdir, mode],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def test_chaos_host_death_is_stolen_and_artifacts_are_byte_stable():
    """Kill host 0 at its first lease (``scheduler_lease``/``die`` fault
    rule -> os._exit(17)); host 1 must finish the whole fleet, recording
    at least one expiry-steal; the artifact set must equal a plain
    single-host build byte-for-byte (training is deterministic and
    device-count-invariant is NOT assumed: both arms run 4 virtual
    devices)."""
    outdir = tempfile.mkdtemp(prefix="gordo-chaos-")
    elastic_dir = os.path.join(outdir, "elastic")
    baseline_dir = os.path.join(outdir, "baseline")
    for d in (elastic_dir, baseline_dir):
        os.makedirs(d)
        with open(os.path.join(d, "config.yaml"), "w") as f:
            yaml.safe_dump(CHAOS_CONFIG, f)
    worker_py = os.path.join(outdir, "chaos_worker.py")
    with open(worker_py, "w") as f:
        f.write(CHAOS_WORKER.format(repo=REPO))

    env_base = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith("XLA_FLAGS") and not k.startswith("GORDO_TPU_")
    }
    chaos_env = dict(
        env_base,
        GORDO_TPU_LEASE_TIMEOUT_S="2",
        GORDO_TPU_HEARTBEAT_S="0.5",
    )
    victim_env = dict(
        chaos_env,
        GORDO_TPU_HOST_ID="victim",
        GORDO_TPU_FAULT_PLAN=json.dumps(
            {"rules": [{"site": "scheduler_lease", "error": "die"}]}
        ),
    )
    survivor_env = dict(chaos_env, GORDO_TPU_HOST_ID="survivor")

    # baseline builds concurrently; victim leases a unit and hard-exits
    baseline = _spawn_chaos_worker(worker_py, 0, baseline_dir, "single", env_base)
    victim = _spawn_chaos_worker(worker_py, 0, elastic_dir, "elastic", victim_env)
    v_out, _ = victim.communicate(timeout=600)
    assert victim.returncode == 17, f"victim did not die at the fault:\n{v_out[-4000:]}"

    # the survivor starts against the victim's now-stale lease
    survivor = _spawn_chaos_worker(
        worker_py, 1, elastic_dir, "elastic", survivor_env
    )
    s_out, _ = survivor.communicate(timeout=600)
    assert survivor.returncode == 0, f"survivor failed:\n{s_out[-4000:]}"
    b_out, _ = baseline.communicate(timeout=600)
    assert baseline.returncode == 0, f"baseline failed:\n{b_out[-4000:]}"

    stats_lines = [l for l in s_out.splitlines() if l.startswith("STATS ")]
    assert stats_lines, s_out[-4000:]
    payload = json.loads(stats_lines[-1][len("STATS "):])
    names = sorted(m["name"] for m in CHAOS_CONFIG["machines"])
    # the survivor finished the victim's work: full fleet, >=1 expiry-steal
    assert payload["built"] == names
    assert payload["stats"]["lease_expirations"] >= 1
    assert payload["stats"]["leases_steal"] >= 1

    for name in names:
        stolen_pkl = os.path.join(elastic_dir, "models", name, "model.pkl")
        base_pkl = os.path.join(baseline_dir, "models", name, "model.pkl")
        assert os.path.exists(stolen_pkl), f"{name}: missing elastic artifact"
        assert os.path.exists(base_pkl), f"{name}: missing baseline artifact"
        assert _sha256(stolen_pkl) == _sha256(base_pkl), (
            f"{name}: elastic artifact differs from single-host build"
        )
