import numpy as np
import pandas as pd
import pytest

from gordo_tpu.dataset import (
    GordoBaseDataset,
    InsufficientDataError,
    RandomDataProvider,
    SensorTag,
    TimeSeriesDataset,
    normalize_sensor_tag,
    normalize_sensor_tags,
)


def test_normalize_sensor_tag_forms():
    assert normalize_sensor_tag("TAG-1") == SensorTag("TAG-1", None)
    assert normalize_sensor_tag("TAG-1", asset="a") == SensorTag("TAG-1", "a")
    assert normalize_sensor_tag({"name": "T", "asset": "a"}) == SensorTag("T", "a")
    assert normalize_sensor_tag(["T", "a"]) == SensorTag("T", "a")
    assert normalize_sensor_tag(SensorTag("T", "a")) == SensorTag("T", "a")
    with pytest.raises(ValueError):
        normalize_sensor_tag({"noname": 1})


def test_random_provider_deterministic():
    from datetime import datetime, timezone

    provider = RandomDataProvider()
    tags = normalize_sensor_tags(["tag-a", "tag-b"])
    start = datetime(2019, 1, 1, tzinfo=timezone.utc)
    end = datetime(2019, 1, 2, tzinfo=timezone.utc)
    series1 = list(provider.load_series(start, end, tags))
    series2 = list(provider.load_series(start, end, tags))
    assert len(series1) == 2
    assert len(series1[0]) == 144  # one day at 10min
    pd.testing.assert_series_equal(series1[0], series2[0])
    # distinct tags get distinct data
    assert not np.allclose(series1[0].values, series1[1].values)


def test_timeseries_dataset_get_data():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-03T00:00:00+00:00",
        tags=["tag-a", "tag-b"],
        data_provider={"type": "RandomDataProvider"},
    )
    X, y = ds.get_data()
    assert list(X.columns) == ["tag-a", "tag-b"]
    assert X.shape == y.shape
    assert len(X) == 288
    meta = ds.get_metadata()
    assert meta["resolution"] == "10min"
    assert "query_duration_sec" in meta


def test_dataset_from_dict_and_roundtrip():
    config = {
        "type": "RandomDataset",
        "train_start_date": "2019-01-01T00:00:00+00:00",
        "train_end_date": "2019-01-02T00:00:00+00:00",
        "tags": ["tag-a"],
    }
    ds = GordoBaseDataset.from_dict(config)
    d = ds.to_dict()
    assert d["type"] == "RandomDataset"
    ds2 = GordoBaseDataset.from_dict(d)
    X1, _ = ds.get_data()
    X2, _ = ds2.get_data()
    pd.testing.assert_frame_equal(X1, X2)


def test_target_tags_differ():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-02T00:00:00+00:00",
        tags=["tag-a", "tag-b"],
        target_tag_list=["tag-c"],
    )
    X, y = ds.get_data()
    assert list(X.columns) == ["tag-a", "tag-b"]
    assert list(y.columns) == ["tag-c"]


def test_insufficient_data_raises():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-01T01:00:00+00:00",
        tags=["tag-a"],
        n_samples_threshold=10,
    )
    with pytest.raises(InsufficientDataError):
        ds.get_data()


def test_tz_naive_dates_rejected():
    with pytest.raises(ValueError):
        TimeSeriesDataset(
            train_start_date="2019-01-01",
            train_end_date="2019-01-02",
            tags=["tag-a"],
        )


def test_start_after_end_rejected():
    with pytest.raises(ValueError):
        TimeSeriesDataset(
            train_start_date="2019-01-02T00:00:00+00:00",
            train_end_date="2019-01-01T00:00:00+00:00",
            tags=["tag-a"],
        )


def test_multi_aggregation_methods():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-02T00:00:00+00:00",
        tags=["tag-a", "tag-b"],
        aggregation_methods=["mean", "max"],
    )
    X, y = ds.get_data()
    assert list(X.columns) == ["tag-a_mean", "tag-a_max", "tag-b_mean", "tag-b_max"]
    assert (X["tag-a_max"] >= X["tag-a_mean"] - 1e-9).all()


def test_to_dict_preserves_interpolation():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-02T00:00:00+00:00",
        tags=["tag-a"],
        interpolation_limit="48h",
    )
    d = ds.to_dict()
    assert d["interpolation_limit"] == "48h"
    ds2 = GordoBaseDataset.from_dict(d)
    assert ds2.interpolation_limit == "48h"


def test_influx_provider_queries_and_parses():
    """InfluxDataProvider speaks the 1.x /query API; stub session, no network."""
    from datetime import datetime, timezone

    import numpy as np
    import pandas as pd

    from gordo_tpu.dataset.data_provider import (
        GordoBaseDataProvider,
        InfluxDataProvider,
    )
    from gordo_tpu.dataset.sensor_tag import SensorTag

    calls = []

    class StubResp:
        status_code = 200

        def json(self):
            base = pd.Timestamp("2019-01-01", tz="UTC").value
            return {
                "results": [
                    {
                        "series": [
                            {
                                "columns": ["time", "Value"],
                                "values": [
                                    [base, 1.5],
                                    [base + 600_000_000_000, 2.5],
                                ],
                            }
                        ]
                    }
                ]
            }

    class StubSession:
        def get(self, url, params=None, auth=None):
            calls.append((url, params))
            return StubResp()

    provider = InfluxDataProvider(
        uri="http://influx.example:8086/proj-db", session=StubSession()
    )
    start = datetime(2019, 1, 1, tzinfo=timezone.utc)
    end = datetime(2019, 1, 2, tzinfo=timezone.utc)
    tag = SensorTag("pump's-sensor", asset="a")
    (series,) = list(provider.load_series(start, end, [tag]))

    url, params = calls[0]
    assert url == "http://influx.example:8086/query"
    assert params["db"] == "proj-db"
    assert "pump''s-sensor" in params["q"]  # InfluxQL quote escaping
    assert "time >= '2019-01-01T00:00:00.000000Z'" in params["q"]
    assert series.name == tag.name
    np.testing.assert_allclose(series.to_numpy(), [1.5, 2.5])
    assert series.index.tz is not None

    # config round-trip through the registry
    rebuilt = GordoBaseDataProvider.from_dict(provider.to_dict())
    assert isinstance(rebuilt, InfluxDataProvider)
    assert rebuilt.database == "proj-db"


def test_parquet_files_provider(tmp_path):
    """ParquetFilesProvider reads per-tag files (flat or per-asset) and
    windows them to the training range."""
    import numpy as np
    import pandas as pd

    from gordo_tpu.dataset import GordoBaseDataset
    from gordo_tpu.dataset.data_provider import (
        GordoBaseDataProvider,
        ParquetFilesProvider,
    )
    from gordo_tpu.dataset.sensor_tag import SensorTag

    idx = pd.date_range("2019-01-01", periods=500, freq="10min", tz="UTC")
    rng = np.random.RandomState(0)
    (tmp_path / "plant").mkdir()
    pd.DataFrame({"Value": rng.rand(500)}, index=idx).to_parquet(
        tmp_path / "tag-a.parquet"
    )
    pd.DataFrame({"Value": rng.rand(500)}, index=idx).to_parquet(
        tmp_path / "plant" / "tag-b.parquet"
    )

    provider = ParquetFilesProvider(base_path=str(tmp_path))
    start = pd.Timestamp("2019-01-01T10:00:00+00:00")
    end = pd.Timestamp("2019-01-02T00:00:00+00:00")
    tags = [SensorTag("tag-a", asset=None), SensorTag("tag-b", asset="plant")]
    series = list(provider.load_series(start, end, tags))
    assert [s.name for s in series] == ["tag-a", "tag-b"]
    for s in series:
        assert s.index.min() >= start and s.index.max() < end
        assert len(s) == 84  # 14h of 10-min samples

    # through the full dataset layer (resample/join) from a config dict
    dataset = GordoBaseDataset.from_dict(
        {
            "type": "TimeSeriesDataset",
            "tags": ["tag-a", "tag-b"],
            "train_start_date": str(start),
            "train_end_date": str(end),
            "asset": "plant",
            "data_provider": {
                "type": "ParquetFilesProvider",
                "base_path": str(tmp_path),
            },
        }
    )
    X, y = dataset.get_data()
    assert list(X.columns) == ["tag-a", "tag-b"]
    assert len(X) > 50 and np.isfinite(X.to_numpy()).all()

    # registry round-trip
    rebuilt = GordoBaseDataProvider.from_dict(provider.to_dict())
    assert isinstance(rebuilt, ParquetFilesProvider)

    missing = ParquetFilesProvider(base_path=str(tmp_path))
    with pytest.raises(FileNotFoundError):
        list(missing.load_series(start, end, [SensorTag("nope", asset=None)]))


# ----------------------------------------------------- ADLS Gen2 provider
def _parquet_blob(index, values):
    import io

    buf = io.BytesIO()
    pd.DataFrame({"Value": values}, index=index).to_parquet(buf)
    return buf.getvalue()


class _ADLSStub:
    """Fake transport recording every request; serves a per-path blob map."""

    def __init__(self, blobs):
        self.blobs = blobs
        self.calls = []

    def get(self, url, headers=None, params=None):
        self.calls.append({"url": url, "headers": dict(headers or {}),
                           "params": dict(params or {})})

        class Resp:
            pass

        resp = Resp()
        path = url.split(".dfs.core.windows.net", 1)[1]
        if path in self.blobs:
            resp.status_code = 200
            resp.content = self.blobs[path]
            resp.text = ""
        else:
            resp.status_code = 404
            resp.content = b""
            resp.text = "PathNotFound"
        return resp


def test_adls_provider_reads_filters_and_falls_back():
    from gordo_tpu.dataset.data_provider import DataLakeProvider
    from gordo_tpu.dataset.sensor_tag import SensorTag

    index = pd.date_range("2019-01-01", periods=48, freq="10min", tz="UTC")
    values = np.arange(48, dtype=np.float64)
    stub = _ADLSStub({
        "/data/asset-a/tag-0.parquet": _parquet_blob(index, values),
        "/data/tag-1.parquet": _parquet_blob(index, values * 2),  # asset-less
    })
    provider = DataLakeProvider(
        store_name="acct", sas_token="sv=2021&sig=xyz", session=stub
    )
    start = pd.Timestamp("2019-01-01T01:00:00Z")
    end = pd.Timestamp("2019-01-01T03:00:00Z")
    got = list(provider.load_series(
        start, end,
        [SensorTag("tag-0", "asset-a"), SensorTag("tag-1", "asset-a")],
    ))
    assert len(got) == 2
    # [start, end) window filtering
    assert got[0].index.min() >= start and got[0].index.max() < end
    assert len(got[0]) == 12
    # tag-1 missing under the asset -> fell back to the asset-less path
    tried = [c["url"] for c in stub.calls]
    assert any(u.endswith("/data/asset-a/tag-1.parquet") for u in tried)
    assert any(u.endswith("/data/tag-1.parquet") for u in tried)
    np.testing.assert_allclose(got[1].to_numpy()[:3], [12.0, 14.0, 16.0])
    # SAS params rode the query string
    assert stub.calls[0]["params"] == {"sv": "2021", "sig": "xyz"}


def test_adls_shared_key_signature_verifiable():
    """SharedKey auth: recompute the documented HMAC over the canonicalized
    request and match the Authorization header the provider sent."""
    import base64
    import hashlib
    import hmac as hmac_mod

    from gordo_tpu.dataset.data_provider import DataLakeProvider
    from gordo_tpu.dataset.sensor_tag import SensorTag

    index = pd.date_range("2019-01-01", periods=4, freq="10min", tz="UTC")
    stub = _ADLSStub({"/data/t.parquet": _parquet_blob(index, np.ones(4))})
    key = base64.b64encode(b"0123456789abcdef").decode()
    provider = DataLakeProvider(store_name="acct", account_key=key, session=stub)
    list(provider.load_series(index[0], index[-1], [SensorTag("t", "")]))

    call = stub.calls[0]
    auth = call["headers"]["Authorization"]
    assert auth.startswith("SharedKey acct:")
    ms = sorted(
        (k.lower(), v) for k, v in call["headers"].items()
        if k.lower().startswith("x-ms-")
    )
    string_to_sign = (
        "GET" + "\n" * 12
        + "".join(f"{k}:{v}\n" for k, v in ms)
        + "/acct/data/t.parquet"
    )
    expected = base64.b64encode(
        hmac_mod.new(
            base64.b64decode(key), string_to_sign.encode(), hashlib.sha256
        ).digest()
    ).decode()
    assert auth == f"SharedKey acct:{expected}"
    assert call["headers"]["x-ms-version"] == provider.API_VERSION
    assert "x-ms-date" in call["headers"]


def test_adls_provider_credential_and_config_handling(monkeypatch):
    from gordo_tpu.dataset.data_provider import (
        DataLakeProvider, GordoBaseDataProvider,
    )
    from gordo_tpu.dataset.sensor_tag import SensorTag

    # no credentials -> clear error at first read
    monkeypatch.delenv("AZURE_STORAGE_SAS_TOKEN", raising=False)
    monkeypatch.delenv("AZURE_STORAGE_TOKEN", raising=False)
    monkeypatch.delenv("AZURE_STORAGE_KEY", raising=False)
    provider = DataLakeProvider(store_name="acct", session=_ADLSStub({}))
    with pytest.raises(ValueError, match="no credentials"):
        list(provider.load_series(
            pd.Timestamp("2019-01-01", tz="UTC"),
            pd.Timestamp("2019-01-02", tz="UTC"),
            [SensorTag("t", "")],
        ))

    # reference API compat: storename= accepted, interactive refused
    assert DataLakeProvider(storename="legacy", session=_ADLSStub({})).store_name == "legacy"
    with pytest.raises(ValueError, match="interactive"):
        DataLakeProvider(store_name="acct", interactive=True)

    # round-trip through from_dict/to_dict NEVER carries credentials
    provider = DataLakeProvider(
        store_name="acct", sas_token="sig=secret", session=_ADLSStub({})
    )
    config = provider.to_dict()
    assert "secret" not in str(config)
    rebuilt = GordoBaseDataProvider.from_dict(config)
    assert isinstance(rebuilt, DataLakeProvider)
    assert rebuilt.store_name == "acct"

    # bearer token from env
    monkeypatch.setenv("AZURE_STORAGE_TOKEN", "aad-token")
    index = pd.date_range("2019-01-01", periods=4, freq="10min", tz="UTC")
    stub = _ADLSStub({"/data/t.parquet": _parquet_blob(index, np.ones(4))})
    provider = DataLakeProvider(store_name="acct", session=stub)
    list(provider.load_series(index[0], index[-1], [SensorTag("t", "")]))
    assert stub.calls[0]["headers"]["Authorization"] == "Bearer aad-token"


def test_adls_sas_and_path_encoding():
    """Percent-encoded SAS values decode once (requests re-encodes on the
    wire), and tag names with '#'/spaces quote into the URL path instead of
    becoming fragments."""
    from gordo_tpu.dataset.data_provider import DataLakeProvider
    from gordo_tpu.dataset.sensor_tag import SensorTag

    index = pd.date_range("2019-01-01", periods=4, freq="10min", tz="UTC")
    stub = _ADLSStub({"/data/1000%23A%20B.parquet": _parquet_blob(index, np.ones(4))})
    provider = DataLakeProvider(
        store_name="acct", sas_token="sig=ab%2Bcd%3D&sv=2021", session=stub
    )
    got = list(provider.load_series(index[0], index[-1], [SensorTag("1000#A B", "")]))
    assert len(got) == 1 and len(got[0]) == 3
    call = stub.calls[0]
    # decoded exactly once: the raw '+'/'=' are restored for requests to re-encode
    assert call["params"] == {"sig": "ab+cd=", "sv": "2021"}
    assert call["url"].endswith("/data/1000%23A%20B.parquet")


def test_adls_custom_template_fallback_keeps_prefix():
    from gordo_tpu.dataset.data_provider import DataLakeProvider
    from gordo_tpu.dataset.sensor_tag import SensorTag

    index = pd.date_range("2019-01-01", periods=4, freq="10min", tz="UTC")
    stub = _ADLSStub({"/data/timeseries/t.parquet": _parquet_blob(index, np.ones(4))})
    provider = DataLakeProvider(
        store_name="acct", sas_token="sig=x", session=stub,
        path_template="timeseries/{asset}/{tag}.{format}",
    )
    got = list(provider.load_series(index[0], index[-1], [SensorTag("t", "plant")]))
    assert len(got) == 1 and len(got[0]) == 3
    tried = [c["url"] for c in stub.calls]
    assert tried[0].endswith("/data/timeseries/plant/t.parquet")
    assert tried[1].endswith("/data/timeseries/t.parquet")  # prefix preserved


def test_adls_explicit_credential_beats_stale_env(monkeypatch):
    from gordo_tpu.dataset.data_provider import DataLakeProvider

    monkeypatch.setenv("AZURE_STORAGE_SAS_TOKEN", "sig=stale")
    import base64
    key = base64.b64encode(b"k").decode()
    provider = DataLakeProvider(store_name="acct", account_key=key)
    assert provider.sas_token is None
    assert provider.account_key == key


def test_adls_missing_file_raises_ioerror():
    from gordo_tpu.dataset.data_provider import DataLakeProvider
    from gordo_tpu.dataset.sensor_tag import SensorTag

    provider = DataLakeProvider(
        store_name="acct", sas_token="sig=x", session=_ADLSStub({})
    )
    with pytest.raises(IOError, match="ADLS read failed.*404"):
        list(provider.load_series(
            pd.Timestamp("2019-01-01", tz="UTC"),
            pd.Timestamp("2019-01-02", tz="UTC"),
            [SensorTag("absent", "plant")],
        ))


def test_adls_sas_blank_value_param_preserved():
    """Empty-valued SAS params (some generators emit '&sdd=') must survive
    parsing verbatim — dropping one mutates the signed query and 403s."""
    from gordo_tpu.dataset.data_provider import DataLakeProvider
    from gordo_tpu.dataset.sensor_tag import SensorTag

    index = pd.date_range("2019-01-01", periods=4, freq="10min", tz="UTC")
    stub = _ADLSStub({"/data/t.parquet": _parquet_blob(index, np.ones(4))})
    provider = DataLakeProvider(
        store_name="acct", sas_token="sv=2021&sdd=&sig=xyz", session=stub
    )
    got = list(provider.load_series(index[0], index[-1], [SensorTag("t", "")]))
    assert len(got) == 1
    assert stub.calls[0]["params"] == {"sv": "2021", "sdd": "", "sig": "xyz"}


def test_calendar_resolution_builds():
    """Calendar-based resample frequencies ('MS') have no fixed Timedelta;
    the interpolation-limit math must not crash on them (it uses the
    joined frame's actual bucket spacing instead)."""
    from gordo_tpu.dataset.datasets import TimeSeriesDataset

    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-07-01T00:00:00+00:00",
        tags=["cal-0", "cal-1"],
        data_provider={"type": "RandomDataProvider"},
        resolution="MS",
        n_samples_threshold=0,
    )
    X, y = ds.get_data()
    assert len(X) >= 3  # monthly buckets over six months
    assert list(X.columns) == ["cal-0", "cal-1"]
