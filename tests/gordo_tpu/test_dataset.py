import numpy as np
import pandas as pd
import pytest

from gordo_tpu.dataset import (
    GordoBaseDataset,
    InsufficientDataError,
    RandomDataProvider,
    SensorTag,
    TimeSeriesDataset,
    normalize_sensor_tag,
    normalize_sensor_tags,
)


def test_normalize_sensor_tag_forms():
    assert normalize_sensor_tag("TAG-1") == SensorTag("TAG-1", None)
    assert normalize_sensor_tag("TAG-1", asset="a") == SensorTag("TAG-1", "a")
    assert normalize_sensor_tag({"name": "T", "asset": "a"}) == SensorTag("T", "a")
    assert normalize_sensor_tag(["T", "a"]) == SensorTag("T", "a")
    assert normalize_sensor_tag(SensorTag("T", "a")) == SensorTag("T", "a")
    with pytest.raises(ValueError):
        normalize_sensor_tag({"noname": 1})


def test_random_provider_deterministic():
    from datetime import datetime, timezone

    provider = RandomDataProvider()
    tags = normalize_sensor_tags(["tag-a", "tag-b"])
    start = datetime(2019, 1, 1, tzinfo=timezone.utc)
    end = datetime(2019, 1, 2, tzinfo=timezone.utc)
    series1 = list(provider.load_series(start, end, tags))
    series2 = list(provider.load_series(start, end, tags))
    assert len(series1) == 2
    assert len(series1[0]) == 144  # one day at 10min
    pd.testing.assert_series_equal(series1[0], series2[0])
    # distinct tags get distinct data
    assert not np.allclose(series1[0].values, series1[1].values)


def test_timeseries_dataset_get_data():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-03T00:00:00+00:00",
        tags=["tag-a", "tag-b"],
        data_provider={"type": "RandomDataProvider"},
    )
    X, y = ds.get_data()
    assert list(X.columns) == ["tag-a", "tag-b"]
    assert X.shape == y.shape
    assert len(X) == 288
    meta = ds.get_metadata()
    assert meta["resolution"] == "10min"
    assert "query_duration_sec" in meta


def test_dataset_from_dict_and_roundtrip():
    config = {
        "type": "RandomDataset",
        "train_start_date": "2019-01-01T00:00:00+00:00",
        "train_end_date": "2019-01-02T00:00:00+00:00",
        "tags": ["tag-a"],
    }
    ds = GordoBaseDataset.from_dict(config)
    d = ds.to_dict()
    assert d["type"] == "RandomDataset"
    ds2 = GordoBaseDataset.from_dict(d)
    X1, _ = ds.get_data()
    X2, _ = ds2.get_data()
    pd.testing.assert_frame_equal(X1, X2)


def test_target_tags_differ():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-02T00:00:00+00:00",
        tags=["tag-a", "tag-b"],
        target_tag_list=["tag-c"],
    )
    X, y = ds.get_data()
    assert list(X.columns) == ["tag-a", "tag-b"]
    assert list(y.columns) == ["tag-c"]


def test_insufficient_data_raises():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-01T01:00:00+00:00",
        tags=["tag-a"],
        n_samples_threshold=10,
    )
    with pytest.raises(InsufficientDataError):
        ds.get_data()


def test_tz_naive_dates_rejected():
    with pytest.raises(ValueError):
        TimeSeriesDataset(
            train_start_date="2019-01-01",
            train_end_date="2019-01-02",
            tags=["tag-a"],
        )


def test_start_after_end_rejected():
    with pytest.raises(ValueError):
        TimeSeriesDataset(
            train_start_date="2019-01-02T00:00:00+00:00",
            train_end_date="2019-01-01T00:00:00+00:00",
            tags=["tag-a"],
        )


def test_multi_aggregation_methods():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-02T00:00:00+00:00",
        tags=["tag-a", "tag-b"],
        aggregation_methods=["mean", "max"],
    )
    X, y = ds.get_data()
    assert list(X.columns) == ["tag-a_mean", "tag-a_max", "tag-b_mean", "tag-b_max"]
    assert (X["tag-a_max"] >= X["tag-a_mean"] - 1e-9).all()


def test_to_dict_preserves_interpolation():
    ds = TimeSeriesDataset(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-02T00:00:00+00:00",
        tags=["tag-a"],
        interpolation_limit="48h",
    )
    d = ds.to_dict()
    assert d["interpolation_limit"] == "48h"
    ds2 = GordoBaseDataset.from_dict(d)
    assert ds2.interpolation_limit == "48h"


def test_influx_provider_queries_and_parses():
    """InfluxDataProvider speaks the 1.x /query API; stub session, no network."""
    from datetime import datetime, timezone

    import numpy as np
    import pandas as pd

    from gordo_tpu.dataset.data_provider import (
        GordoBaseDataProvider,
        InfluxDataProvider,
    )
    from gordo_tpu.dataset.sensor_tag import SensorTag

    calls = []

    class StubResp:
        status_code = 200

        def json(self):
            base = pd.Timestamp("2019-01-01", tz="UTC").value
            return {
                "results": [
                    {
                        "series": [
                            {
                                "columns": ["time", "Value"],
                                "values": [
                                    [base, 1.5],
                                    [base + 600_000_000_000, 2.5],
                                ],
                            }
                        ]
                    }
                ]
            }

    class StubSession:
        def get(self, url, params=None, auth=None):
            calls.append((url, params))
            return StubResp()

    provider = InfluxDataProvider(
        uri="http://influx.example:8086/proj-db", session=StubSession()
    )
    start = datetime(2019, 1, 1, tzinfo=timezone.utc)
    end = datetime(2019, 1, 2, tzinfo=timezone.utc)
    tag = SensorTag("pump's-sensor", asset="a")
    (series,) = list(provider.load_series(start, end, [tag]))

    url, params = calls[0]
    assert url == "http://influx.example:8086/query"
    assert params["db"] == "proj-db"
    assert "pump''s-sensor" in params["q"]  # InfluxQL quote escaping
    assert "time >= '2019-01-01T00:00:00.000000Z'" in params["q"]
    assert series.name == tag.name
    np.testing.assert_allclose(series.to_numpy(), [1.5, 2.5])
    assert series.index.tz is not None

    # config round-trip through the registry
    rebuilt = GordoBaseDataProvider.from_dict(provider.to_dict())
    assert isinstance(rebuilt, InfluxDataProvider)
    assert rebuilt.database == "proj-db"


def test_parquet_files_provider(tmp_path):
    """ParquetFilesProvider reads per-tag files (flat or per-asset) and
    windows them to the training range."""
    import numpy as np
    import pandas as pd

    from gordo_tpu.dataset import GordoBaseDataset
    from gordo_tpu.dataset.data_provider import (
        GordoBaseDataProvider,
        ParquetFilesProvider,
    )
    from gordo_tpu.dataset.sensor_tag import SensorTag

    idx = pd.date_range("2019-01-01", periods=500, freq="10min", tz="UTC")
    rng = np.random.RandomState(0)
    (tmp_path / "plant").mkdir()
    pd.DataFrame({"Value": rng.rand(500)}, index=idx).to_parquet(
        tmp_path / "tag-a.parquet"
    )
    pd.DataFrame({"Value": rng.rand(500)}, index=idx).to_parquet(
        tmp_path / "plant" / "tag-b.parquet"
    )

    provider = ParquetFilesProvider(base_path=str(tmp_path))
    start = pd.Timestamp("2019-01-01T10:00:00+00:00")
    end = pd.Timestamp("2019-01-02T00:00:00+00:00")
    tags = [SensorTag("tag-a", asset=None), SensorTag("tag-b", asset="plant")]
    series = list(provider.load_series(start, end, tags))
    assert [s.name for s in series] == ["tag-a", "tag-b"]
    for s in series:
        assert s.index.min() >= start and s.index.max() < end
        assert len(s) == 84  # 14h of 10-min samples

    # through the full dataset layer (resample/join) from a config dict
    dataset = GordoBaseDataset.from_dict(
        {
            "type": "TimeSeriesDataset",
            "tags": ["tag-a", "tag-b"],
            "train_start_date": str(start),
            "train_end_date": str(end),
            "asset": "plant",
            "data_provider": {
                "type": "ParquetFilesProvider",
                "base_path": str(tmp_path),
            },
        }
    )
    X, y = dataset.get_data()
    assert list(X.columns) == ["tag-a", "tag-b"]
    assert len(X) > 50 and np.isfinite(X.to_numpy()).all()

    # registry round-trip
    rebuilt = GordoBaseDataProvider.from_dict(provider.to_dict())
    assert isinstance(rebuilt, ParquetFilesProvider)

    missing = ParquetFilesProvider(base_path=str(tmp_path))
    with pytest.raises(FileNotFoundError):
        list(missing.load_series(start, end, [SensorTag("nope", asset=None)]))
