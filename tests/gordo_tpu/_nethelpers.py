"""Shared network-test helpers (used by test_dockertest, test_server_pool
and test_distributed — keep one copy so fixes don't silently miss a twin)."""

import socket
import time


def free_port() -> int:
    """Ephemeral host port — concurrent runs on one host must not collide."""
    with socket.socket() as sock:
        sock.bind(("", 0))
        return sock.getsockname()[1]


def wait_for(probe, timeout: float = 30.0) -> bool:
    """Poll ``probe()`` (exceptions count as not-ready) until truthy."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            if probe():
                return True
        except Exception:
            pass
        time.sleep(0.5)
    return False
