"""
Request tracing, the flight recorder, and the /debug endpoints (ISSUE 5).

The headline test is the deterministic end-to-end: a fault-plan wedge on
the fused device call + concurrent clients, then /debug/flight must hold
the wedged requests' full span trees — root request span, batcher queue
span, device-call span with span-links to the co-fused riders — with the
same trace_id in the JSON log capture and the X-Gordo-Trace response
header.
"""

import json
import logging
import threading

import numpy as np
import pytest

from gordo_tpu.observability import flight, logs, telemetry, tracing
from gordo_tpu.observability import metrics as metric_catalog
from gordo_tpu.observability.tracing import RequestTrace, SpanRecord
from gordo_tpu.server import resilience
from gordo_tpu.util import faults


@pytest.fixture(autouse=True)
def _clean_state():
    faults.reset_plan()
    resilience.reset_for_tests()
    flight.reset()
    telemetry.reset()
    yield
    faults.reset_plan()
    resilience.reset_for_tests()
    flight.reset()
    telemetry.reset()


# ----------------------------------------------------------- trace context
def test_traceparent_roundtrip():
    ctx = tracing.fresh_context()
    header = tracing.format_traceparent(ctx)
    parsed = tracing.parse_traceparent(header)
    assert parsed == (ctx.trace_id, ctx.span_id)


@pytest.mark.parametrize(
    "header",
    [
        None,
        "",
        "garbage",
        "00-zz" + "0" * 30 + "-" + "1" * 16 + "-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # all-zero trace id
        "00-" + "a" * 32 + "-" + "0" * 16 + "-01",  # all-zero span id
        "00-" + "a" * 31 + "-" + "1" * 16 + "-01",  # short trace id
    ],
)
def test_malformed_traceparent_rejected(header):
    assert tracing.parse_traceparent(header) is None


def test_span_tree_parents_follow_context():
    with tracing.request_root(None) as root:
        with telemetry.span("serve_request") as outer:
            with telemetry.span("serve_decode"):
                pass
            with telemetry.span("serve_predict"):
                with telemetry.span("serve_batch_queue"):
                    pass
    spans = {s.name: s for s in root.collector.snapshot()}
    assert set(spans) == {
        "serve_request", "serve_decode", "serve_predict", "serve_batch_queue",
    }
    req = spans["serve_request"]
    assert req.parent_id is None
    assert spans["serve_decode"].parent_id == req.span_id
    assert spans["serve_predict"].parent_id == req.span_id
    assert (
        spans["serve_batch_queue"].parent_id == spans["serve_predict"].span_id
    )
    assert all(s.trace_id == root.trace_id for s in spans.values())
    assert outer is not None  # real span, not the disabled singleton


def test_inbound_traceparent_sets_root_parent():
    remote_trace, remote_span = "ab" * 16, "cd" * 8
    with tracing.request_root(f"00-{remote_trace}-{remote_span}-01") as root:
        with telemetry.span("serve_request"):
            pass
    (req,) = root.collector.snapshot()
    assert root.trace_id == remote_trace
    assert req.trace_id == remote_trace
    assert req.parent_id == remote_span


def test_span_disabled_path_still_singleton():
    # outside any request context the hot path stays allocation-free
    assert telemetry.span("a") is telemetry.span("b")


def test_capture_attach_across_threads():
    captured = {}

    with tracing.request_root(None) as root:
        with telemetry.span("serve_batch_queue"):
            ctx = tracing.capture()

    def dispatcher():
        tracing.record_into(
            ctx, "serve_device_call", tracing.monotonic(), 0.01,
            links=[("ff" * 16, "ee" * 8)], fused=2,
        )
        captured["done"] = True

    t = threading.Thread(target=dispatcher)
    t.start()
    t.join()
    assert captured["done"]
    spans = {s.name: s for s in root.collector.snapshot()}
    call = spans["serve_device_call"]
    assert call.parent_id == spans["serve_batch_queue"].span_id
    assert call.links == (("ff" * 16, "ee" * 8),)


def test_request_trace_bounded():
    trace = RequestTrace("ab" * 16)
    for i in range(RequestTrace.MAX_SPANS + 10):
        trace.add(
            SpanRecord(f"s{i}", trace.trace_id, f"{i:016x}", None, 0.0, 0.0)
        )
    assert len(trace) == RequestTrace.MAX_SPANS
    assert trace.dropped == 10


def test_machine_roots_memoized():
    a1, a2, b = (
        tracing.root_for("machine-a"),
        tracing.root_for("machine-a"),
        tracing.root_for("machine-b"),
    )
    assert a1.trace_id == a2.trace_id
    assert a1.trace_id != b.trace_id
    tracing.reset_roots()
    assert tracing.root_for("machine-a").trace_id != a1.trace_id


# ---------------------------------------------------------- flight recorder
def test_flight_classification(monkeypatch):
    recorder = flight.FlightRecorder(capacity=8)
    # cold adaptive threshold: nothing successful is "slow" yet
    assert recorder.classify(200, 10.0) is None
    assert recorder.classify(503, 0.001) == "error"
    monkeypatch.setenv("GORDO_TPU_FLIGHT_SLOW_S", "0.5")
    assert recorder.classify(200, 0.6) == "slow"
    assert recorder.classify(200, 0.4) is None


def test_flight_adaptive_threshold_learns_p99():
    recorder = flight.FlightRecorder(capacity=8)
    for _ in range(200):
        recorder.observe(None, status=200, duration_s=0.01)
    threshold = recorder.slow_threshold_s()
    # ~p99 of the 10ms population, floored at the adaptive minimum
    assert threshold == pytest.approx(flight._ADAPTIVE_FLOOR_S)
    assert recorder.classify(200, flight._ADAPTIVE_FLOOR_S + 0.01) == "slow"


def test_flight_errors_survive_slow_flood(monkeypatch):
    """Tail-sampling keeps errored traces over fast/slow ones: a flood of
    slow-but-successful requests must never evict the error exemplars."""
    monkeypatch.setenv("GORDO_TPU_FLIGHT_SLOW_S", "0.1")
    recorder = flight.FlightRecorder(capacity=8)
    error_ids = []
    for i in range(3):
        trace = RequestTrace(tracing.new_trace_id())
        error_ids.append(trace.trace_id)
        assert recorder.observe(trace, status=500, duration_s=0.01) == "error"
    for i in range(100):
        trace = RequestTrace(tracing.new_trace_id())
        assert recorder.observe(trace, status=200, duration_s=1.0) == "slow"
    kept = {r["trace_id"]: r["class"] for r in recorder.snapshot()}
    for trace_id in error_ids:
        assert kept[trace_id] == "error"
    assert len(kept) <= 8


def test_flight_concurrency_8_writers(monkeypatch):
    """8 writer threads: the ring stays bounded, no span tree is ever torn
    (every span in a kept record carries that record's trace_id), and
    errored traces survive the concurrent slow flood."""
    monkeypatch.setenv("GORDO_TPU_FLIGHT_SLOW_S", "0.1")
    recorder = flight.FlightRecorder(capacity=16)
    n_threads, per_thread = 8, 50
    stop = threading.Event()
    torn = []

    def writer(thread_idx):
        for i in range(per_thread):
            trace = RequestTrace(tracing.new_trace_id())
            parent = None
            for name in ("serve_request", "serve_predict", "serve_encode"):
                span_id = tracing.new_span_id()
                trace.add(
                    SpanRecord(
                        name, trace.trace_id, span_id, parent, 0.0, 0.001
                    )
                )
                parent = span_id
            if i % 5 == 0:
                recorder.observe(trace, status=500, duration_s=0.01)
            else:
                recorder.observe(trace, status=200, duration_s=0.5)

    def reader():
        while not stop.is_set():
            for record in recorder.snapshot():
                bad = [
                    s for s in record["spans"]
                    if s["trace_id"] != record["trace_id"]
                ]
                if bad:
                    torn.append((record["trace_id"], bad))
            recorder.chrome_trace()

    threads = [
        threading.Thread(target=writer, args=(i,)) for i in range(n_threads)
    ]
    observer = threading.Thread(target=reader)
    observer.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    observer.join()

    assert not torn
    records = recorder.snapshot()
    assert 0 < len(records) <= 16
    assert recorder.seen == n_threads * per_thread
    classes = {r["class"] for r in records}
    assert "error" in classes  # errors survived the slow majority
    for record in records:
        names = [s["name"] for s in record["spans"]]
        assert names == ["serve_request", "serve_predict", "serve_encode"]
    # occupancy gauges reflect the per-class rings
    held_err = metric_catalog.FLIGHT_OCCUPANCY.value(cls="error")
    held_slow = metric_catalog.FLIGHT_OCCUPANCY.value(cls="slow")
    assert held_err == len([r for r in records if r["class"] == "error"])
    assert held_slow == len([r for r in records if r["class"] == "slow"])


# -------------------------------------------------------------- JSON logs
def test_json_log_formatter_stamps_trace_ids():
    import io

    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    handler.addFilter(logs.TraceContextFilter())
    handler.setFormatter(logs.JsonLogFormatter())
    log = logging.getLogger("test_tracing.json")
    log.addHandler(handler)
    log.setLevel(logging.INFO)
    try:
        log.info("outside any trace")
        with tracing.request_root(None) as root:
            with telemetry.span("serve_request"):
                log.warning("inside %s", "a trace")
        try:
            raise ValueError("boom")
        except ValueError:
            log.exception("with traceback")
    finally:
        log.removeHandler(handler)
    lines = [json.loads(l) for l in stream.getvalue().strip().splitlines()]
    assert lines[0]["message"] == "outside any trace"
    assert "trace_id" not in lines[0]
    assert lines[1]["message"] == "inside a trace"
    assert lines[1]["trace_id"] == root.trace_id
    assert lines[1]["span_id"]  # the serve_request span was ambient
    assert lines[1]["level"] == "WARNING"
    assert "ValueError: boom" in lines[2]["exc"]


def test_maybe_configure_respects_knob(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_LOG_FORMAT", raising=False)
    assert logs.maybe_configure() is False
    monkeypatch.setenv("GORDO_TPU_LOG_FORMAT", "json")
    root = logging.getLogger()
    before_handlers = list(root.handlers)
    before_formatters = [h.formatter for h in before_handlers]
    try:
        assert logs.maybe_configure() is True
        assert any(
            isinstance(h.formatter, logs.JsonLogFormatter)
            for h in root.handlers
        )
    finally:
        for handler in list(root.handlers):
            if handler not in before_handlers:
                root.removeHandler(handler)
        for handler, formatter in zip(before_handlers, before_formatters):
            handler.setFormatter(formatter)
            for f in list(handler.filters):
                if isinstance(f, logs.TraceContextFilter):
                    handler.removeFilter(f)


# --------------------------------------------------------- debug endpoints
@pytest.fixture()
def app(model_collection_directory, trained_model_directories):
    from gordo_tpu.server import utils as server_utils
    from gordo_tpu.server.server import build_app

    server_utils.clear_model_caches()
    return build_app({"MODEL_COLLECTION_DIR": model_collection_directory})


def test_debug_endpoints_gated_then_live(app, monkeypatch):
    client = app.test_client()
    for path in ("/debug/flight", "/debug/vars", "/debug/config"):
        assert client.get(path).status_code == 404, path

    monkeypatch.setenv("GORDO_TPU_DEBUG_ENDPOINTS", "1")
    resp = client.get("/debug/flight")
    assert resp.status_code == 200
    body = resp.get_json()
    assert "traceEvents" in body and "gordoFlight" in body

    body = client.get("/debug/vars").get_json()
    assert "gordo_server_flight_traces" in body["metrics"]
    assert body["server"]["inflight_requests"] >= 1  # this request
    assert "flight" in body

    monkeypatch.setenv("GORDO_TPU_POSTGRES_PASSWORD", "hunter2")
    monkeypatch.setenv("GORDO_TPU_MAX_INFLIGHT", "3")
    body = client.get("/debug/config").get_json()
    assert body["env"]["GORDO_TPU_POSTGRES_PASSWORD"] == "<redacted>"
    assert body["env"]["GORDO_TPU_MAX_INFLIGHT"] == "3"
    assert body["resolved"]["max_inflight"] == 3
    assert body["resolved"]["debug_endpoints"] is True


# ------------------------------------------------- the deterministic e2e
def test_wedged_fuse_trace_in_flight_recorder_e2e(
    app, gordo_project, gordo_name, monkeypatch
):
    """ISSUE 5 acceptance: fault-plan wedge + concurrent clients → the
    wedged requests' full span trees are retrievable from /debug/flight
    (root request span, batcher queue span, device-call span with
    span-links to co-fused riders), the trace_id matches both the
    X-Gordo-Trace response header and the JSON log capture."""
    import io

    from gordo_tpu.server import batcher as batcher_mod

    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    monkeypatch.setenv("GORDO_TPU_DEBUG_ENDPOINTS", "1")
    # every request that waits out the 0.8s wedge counts as slow
    monkeypatch.setenv("GORDO_TPU_FLIGHT_SLOW_S", "0.25")
    monkeypatch.setenv(
        faults.PLAN_ENV,
        json.dumps(
            {
                "rules": [
                    {
                        "site": "serve_device_call",
                        "times": 1,
                        "error": "wedge",
                        "seconds": 0.8,
                    }
                ]
            }
        ),
    )
    faults.reset_plan()
    flight.reset()

    # JSON log capture on the server logger (what an operator's log
    # pipeline would ingest with GORDO_TPU_LOG_FORMAT=json)
    stream = io.StringIO()
    handler = logging.StreamHandler(stream)
    handler.addFilter(logs.TraceContextFilter())
    handler.setFormatter(logs.JsonLogFormatter())
    server_logger = logging.getLogger("gordo_tpu.server.server")
    old_level = server_logger.level
    server_logger.addHandler(handler)
    server_logger.setLevel(logging.DEBUG)

    n_clients = 4
    trace_ids = [tracing.new_trace_id() for _ in range(n_clients)]
    responses = [None] * n_clients
    X = np.random.RandomState(0).rand(20, 4).tolist()
    body = json.dumps({"X": X}).encode()
    path = f"/gordo/v0/{gordo_project}/{gordo_name}/prediction"
    barrier = threading.Barrier(n_clients)

    def post(i):
        client = app.test_client()
        barrier.wait()
        responses[i] = client.post(
            path,
            data=body,
            content_type="application/json",
            headers={
                "traceparent": f"00-{trace_ids[i]}-{'cd' * 8}-01"
            },
        )

    try:
        threads = [
            threading.Thread(target=post, args=(i,))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    finally:
        server_logger.removeHandler(handler)
        server_logger.setLevel(old_level)

    # every request succeeded (the wedge delays, it does not fail) and
    # echoed ITS trace id back
    for i, resp in enumerate(responses):
        assert resp.status_code == 200, resp.get_data(as_text=True)
        assert resp.headers["X-Gordo-Trace"] == trace_ids[i]

    # the flight recorder kept the wedged requests as slow exemplars
    flight_doc = app.test_client().get("/debug/flight").get_json()
    kept = {r["trace_id"]: r for r in flight_doc["gordoFlight"]}
    wedged_ids = [t for t in trace_ids if t in kept]
    assert wedged_ids, (trace_ids, list(kept))

    events_by_trace = {}
    for event in flight_doc["traceEvents"]:
        events_by_trace.setdefault(
            event["args"]["trace_id"], {}
        ).setdefault(event["name"], []).append(event)

    linked_riders = set()
    for trace_id in wedged_ids:
        spans = events_by_trace[trace_id]
        # full tree: root request span, batcher queue span, device call
        assert "serve_request" in spans, spans.keys()
        assert "serve_batch_queue" in spans, spans.keys()
        assert "serve_device_call" in spans, spans.keys()
        (root,) = spans["serve_request"]
        # the root continued OUR traceparent: its parent is the client span
        assert root["args"]["parent_span_id"] == "cd" * 8
        (queue,) = spans["serve_batch_queue"]
        (call,) = spans["serve_device_call"]
        # the device call is parented under the rider's queue span
        assert call["args"]["parent_span_id"] == queue["args"]["span_id"]
        for link in call["args"].get("links", "").split(","):
            if link:
                linked_riders.add(link.split(":")[0])

    # at least one fused call carried span-links, and every link names
    # another of OUR requests — one slow fuse explains N slow requests
    assert linked_riders, "no device-call span carried span-links"
    assert linked_riders <= set(trace_ids)
    assert any(
        link_target != trace_id
        for trace_id in wedged_ids
        for link_target in linked_riders
    )

    # the JSON log capture carries the same trace ids
    logged = [
        json.loads(line) for line in stream.getvalue().strip().splitlines()
    ]
    logged_ids = {entry.get("trace_id") for entry in logged}
    for trace_id in wedged_ids:
        assert trace_id in logged_ids, (logged_ids, trace_id)
