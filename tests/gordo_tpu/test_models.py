import pickle

import numpy as np
import pytest
import yaml

from gordo_tpu.models.factories.utils import hourglass_calc_dims
from gordo_tpu.models.factories.feedforward_autoencoder import feedforward_hourglass
from gordo_tpu.models.models import (
    AutoEncoder,
    LSTMAutoEncoder,
    LSTMForecast,
    RawModelRegressor,
)
from gordo_tpu.models.register import register_model_builder


@pytest.fixture(scope="module")
def Xy():
    rng = np.random.RandomState(0)
    X = rng.rand(256, 4).astype(np.float32)
    return X, X


def test_hourglass_dims_reference_examples():
    """Dims match the reference factory's documented examples
    (feedforward_autoencoder.py:165-257 docstring)."""
    assert [l.units for l in feedforward_hourglass(10).layers] == [8, 7, 5, 5, 7, 8, 10]
    assert [l.units for l in feedforward_hourglass(5).layers] == [4, 4, 3, 3, 4, 4, 5]
    assert [
        l.units for l in feedforward_hourglass(10, compression_factor=0.2).layers
    ] == [7, 5, 2, 2, 5, 7, 10]
    assert [l.units for l in feedforward_hourglass(10, encoding_layers=1).layers] == [
        5,
        5,
        10,
    ]


def test_hourglass_validations():
    with pytest.raises(ValueError):
        hourglass_calc_dims(1.5, 3, 10)
    with pytest.raises(ValueError):
        hourglass_calc_dims(0.5, 0, 10)


def test_autoencoder_fit_predict_score(Xy):
    X, y = Xy
    model = AutoEncoder(kind="feedforward_hourglass", epochs=2, batch_size=64)
    model.fit(X, y)
    out = model.predict(X)
    assert out.shape == X.shape
    assert isinstance(model.score(X, y), float)
    assert len(model.history["loss"]) == 2
    # training reduces loss
    assert model.history["loss"][-1] <= model.history["loss"][0] * 1.5


def test_autoencoder_invalid_kind():
    with pytest.raises(ValueError):
        AutoEncoder(kind="no_such_factory")


def test_autoencoder_pickle_roundtrip(Xy):
    X, y = Xy
    model = AutoEncoder(
        kind="feedforward_symmetric", dims=(8, 4), funcs=("tanh", "tanh"), epochs=1
    )
    model.fit(X, y)
    out = model.predict(X)
    model2 = pickle.loads(pickle.dumps(model))
    assert np.allclose(model2.predict(X), out, atol=1e-5)
    assert model2.history["loss"] == model.history["loss"]


def test_sklearn_clone_compat():
    from sklearn.base import clone

    model = AutoEncoder(kind="feedforward_hourglass", epochs=3)
    cloned = clone(model)
    assert isinstance(cloned, AutoEncoder)
    assert cloned.kind == "feedforward_hourglass"
    assert cloned.kwargs["epochs"] == 3


def test_seed_determinism(Xy):
    X, y = Xy
    np.random.seed(0)
    m1 = AutoEncoder(kind="feedforward_hourglass", epochs=1)
    m1.fit(X, y)
    np.random.seed(0)
    m2 = AutoEncoder(kind="feedforward_hourglass", epochs=1)
    m2.fit(X, y)
    assert np.allclose(m1.predict(X), m2.predict(X))


def test_custom_callable_kind(Xy):
    X, y = Xy

    def my_model(n_features, n_features_out=None, **kwargs):
        return feedforward_hourglass(n_features, n_features_out, encoding_layers=1)

    model = AutoEncoder(kind=my_model, epochs=1)
    assert model.kind == "my_model"
    assert "my_model" in register_model_builder.factories["AutoEncoder"]
    model.fit(X, y)
    assert model.predict(X).shape == X.shape


@pytest.mark.parametrize(
    "cls,lookahead", [(LSTMAutoEncoder, 0), (LSTMForecast, 1)]
)
def test_lstm_window_semantics(cls, lookahead):
    """Output length = len(X) - lookback + 1 - lookahead (reference
    models.py:715-796 timeseries generator semantics)."""
    rng = np.random.RandomState(1)
    X = rng.rand(120, 3).astype(np.float32)
    model = cls(kind="lstm_hourglass", lookback_window=12, epochs=1, batch_size=32)
    model.fit(X, X)
    out = model.predict(X)
    assert out.shape == (120 - 12 + 1 - lookahead, 3)
    assert model.lookahead == lookahead
    score = model.score(X, X)
    assert isinstance(score, float)


def test_raw_model_regressor():
    config = yaml.safe_load(
        """
        compile:
          loss: mse
          optimizer: adam
        spec:
          layers:
            - Dense:
                units: 8
                activation: tanh
            - Dense:
                units: 2
        """
    )
    rng = np.random.RandomState(2)
    X = rng.rand(64, 4).astype(np.float32)
    y = rng.rand(64, 2).astype(np.float32)
    model = RawModelRegressor(kind=config, epochs=1)
    model.fit(X, y)
    assert model.predict(X).shape == (64, 2)


def test_early_stopping_callback(Xy):
    X, y = Xy
    from gordo_tpu.models.callbacks import EarlyStopping

    # min_delta=10 means no epoch ever counts as an improvement after the
    # first, so patience=2 stops training at epoch 3
    model = AutoEncoder(
        kind="feedforward_hourglass",
        epochs=50,
        callbacks=[EarlyStopping(monitor="loss", patience=2, min_delta=10.0)],
    )
    model.fit(X, y)
    assert len(model.history["loss"]) == 3


def test_validation_split(Xy):
    X, y = Xy
    model = AutoEncoder(kind="feedforward_hourglass", epochs=2, validation_split=0.2)
    model.fit(X, y)
    assert "val_loss" in model.history
    assert len(model.history["val_loss"]) == 2


def test_lstm_predict_pow2_boundary():
    """Regression: windowed predict when n_out is a power of two must not
    under-allocate the padded series (lookahead >= 1 case)."""
    rng = np.random.RandomState(3)
    X = rng.rand(11, 2).astype(np.float32)
    model = LSTMForecast(kind="lstm_hourglass", lookback_window=3, epochs=1)
    model.fit(X, X)
    out = model.predict(X)
    assert out.shape == (11 - 3 + 1 - 1, 2)


def test_keras_callback_path_alias(Xy):
    """Reference configs with tensorflow.keras callback paths still work."""
    import yaml
    from gordo_tpu.serializer import from_definition

    X, y = Xy
    model = from_definition(yaml.safe_load("""
    gordo_tpu.models.models.AutoEncoder:
      kind: feedforward_hourglass
      epochs: 4
      callbacks:
        - tensorflow.keras.callbacks.EarlyStopping:
            monitor: loss
            patience: 1
            min_delta: 100.0
    """))
    model.fit(X, y)
    assert len(model.history["loss"]) == 2


def test_bfloat16_compute_dtype():
    """compute_dtype=bfloat16 runs the forward in bf16 (TPU MXU-native) while
    params, loss and outputs stay float32; accuracy stays in the same ballpark
    as float32 for these small models."""
    import numpy as np

    from gordo_tpu.models import models

    rng = np.random.RandomState(0)
    X = rng.rand(200, 4).astype(np.float32)

    f32 = models.AutoEncoder(kind="feedforward_hourglass", epochs=3)
    f32.fit(X, X)
    bf16 = models.AutoEncoder(
        kind="feedforward_hourglass", epochs=3, compute_dtype="bfloat16"
    )
    bf16.fit(X, X)
    assert bf16.spec_.compute_dtype == "bfloat16"
    # params stored float32
    import jax

    assert all(
        leaf.dtype == np.float32
        for leaf in jax.tree_util.tree_leaves(bf16.params_)
        if hasattr(leaf, "dtype")
    )
    out = bf16.predict(X)
    assert out.dtype == np.float32
    # same ballpark reconstruction as f32 (loose: bf16 has ~3 decimal digits)
    err_f32 = float(np.mean((f32.predict(X) - X) ** 2))
    err_bf16 = float(np.mean((out - X) ** 2))
    assert err_bf16 < max(4 * err_f32, 0.2), (err_bf16, err_f32)
    # round-trips through the definition DSL
    from gordo_tpu.serializer import from_definition, into_definition

    clone = from_definition(into_definition(bf16))
    assert clone.kwargs.get("compute_dtype") == "bfloat16"


def test_bfloat16_lstm_accuracy_and_raw_regressor():
    """bf16 must hold up on the recurrent family (cell state accumulates in
    float32 across the scan) and apply uniformly to RawModelRegressor."""
    import numpy as np

    from gordo_tpu.models import models

    rng = np.random.RandomState(1)
    t = np.arange(300)
    base = np.stack([np.sin(0.1 * t + p) for p in range(4)], axis=1)
    X = (base + 0.05 * rng.randn(300, 4)).astype(np.float32)

    kwargs = dict(kind="lstm_hourglass", lookback_window=12, epochs=3,
                  batch_size=32)
    f32 = models.LSTMAutoEncoder(**kwargs)
    f32.fit(X, X)
    bf16 = models.LSTMAutoEncoder(compute_dtype="bfloat16", **kwargs)
    bf16.fit(X, X)
    n = len(bf16.predict(X))
    err_f32 = float(np.mean((f32.predict(X) - X[-n:]) ** 2))
    err_bf16 = float(np.mean((bf16.predict(X) - X[-n:]) ** 2))
    assert err_bf16 < max(4 * err_f32, 0.2), (err_bf16, err_f32)

    raw = models.RawModelRegressor(
        kind={
            "spec": {
                "layers": [
                    {"Dense": {"units": 8, "activation": "tanh"}},
                    {"Dense": {"units": 4, "activation": "linear"}},
                ]
            },
            "compile": {"loss": "mse"},
        },
        compute_dtype="bfloat16",
        epochs=1,
    )
    raw.fit(X, X)
    assert raw.spec_.compute_dtype == "bfloat16"
    assert np.all(np.isfinite(raw.predict(X)))


def test_remat_is_numerically_identity():
    """remat=True recomputes activations on the backward pass — same math,
    same trained weights; only the memory/FLOPs trade changes."""
    from gordo_tpu.models import models

    rng = np.random.RandomState(3)
    X = rng.rand(160, 4).astype(np.float32)
    kwargs = dict(
        kind="transformer_model", lookback_window=16, d_model=16,
        num_heads=2, ff_dim=32, num_blocks=1, epochs=2, batch_size=32,
    )
    np.random.seed(42)
    plain = models.TransformerAutoEncoder(**kwargs)
    plain.fit(X, X)
    np.random.seed(42)
    remat = models.TransformerAutoEncoder(remat=True, **kwargs)
    remat.fit(X, X)
    assert remat.spec_.remat and not plain.spec_.remat
    np.testing.assert_allclose(
        plain.predict(X), remat.predict(X), rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        plain.history["loss"], remat.history["loss"], rtol=1e-5
    )


def test_remat_grad_contains_checkpoint():
    import jax
    import jax.numpy as jnp

    from gordo_tpu.ops.nn import apply_model, init_model_params

    spec = LSTMAutoEncoder(
        kind="lstm_symmetric", dims=[8], funcs=["tanh"], lookback_window=8,
        remat=True,
    ).build_spec(4, 4)
    assert spec.remat
    params = init_model_params(jax.random.PRNGKey(0), spec)
    x = jnp.zeros((4, 8, 4), jnp.float32)

    def loss(p):
        out, _ = apply_model(spec, p, x)
        return jnp.sum(out ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss))(params)
    assert "remat" in str(jaxpr)


def test_remat_roundtrips_through_definition():
    from gordo_tpu.serializer import from_definition, into_definition

    d = {"gordo_tpu.models.models.TransformerAutoEncoder": {
        "kind": "transformer_model", "lookback_window": 16, "remat": True}}
    model = from_definition(d)
    back = into_definition(model)
    assert back["gordo_tpu.models.models.TransformerAutoEncoder"]["remat"] is True


def test_artifact_params_committed_to_device_once():
    """Artifact-loaded (pickled) params are host numpy; the first predict
    must commit them to device so later jitted calls stop re-staging the
    whole pytree per request — on an accelerator that re-upload was the
    serving p50."""
    import pickle

    import jax

    model = AutoEncoder(kind="feedforward_hourglass", epochs=1)
    X = np.random.RandomState(5).rand(64, 4).astype(np.float32)
    model.fit(X, X)
    loaded = pickle.loads(pickle.dumps(model))
    assert all(
        isinstance(leaf, np.ndarray)
        for leaf in jax.tree_util.tree_leaves(loaded.params_)
    )
    out1 = loaded.predict(X[:16])
    assert all(
        isinstance(leaf, jax.Array)
        for leaf in jax.tree_util.tree_leaves(loaded.params_)
    )
    np.testing.assert_allclose(out1, model.predict(X[:16]), rtol=1e-5, atol=1e-6)
