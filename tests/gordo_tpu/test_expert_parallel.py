"""
MoE Transformer + expert parallelism on the 8-virtual-device CPU mesh.

Contracts: Switch-style routing (top-1, hard capacity, over-capacity
pass-through) is identical between the single-device path and the
expert-sharded shard_map (same cumsum positions -> same drops), EP specs
keep off both vmap paths, and the MoE family rides the normal config /
serializer / builder machinery.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gordo_tpu.models.models import TransformerAutoEncoder
from gordo_tpu.models.spec import MoEBlock
from gordo_tpu.ops.nn import (
    _apply_moe_block,
    init_moe_block,
    moe_capacity,
    moe_dispatch_ffn,
)
from gordo_tpu.parallel.expert_parallel import (
    apply_ep_moe_block,
    ep_degree,
    prepare_ep_spec,
)

N_TAGS = 4
MOE_KW = dict(
    kind="moe_transformer_model",
    lookback_window=16,
    d_model=16,
    num_heads=2,
    num_experts=8,
    expert_dim=32,
    num_blocks=2,
    epochs=2,
    batch_size=32,
)


def _block(**over):
    base = dict(d_model=16, num_heads=2, num_experts=8, expert_dim=32,
                attention_impl="xla")
    base.update(over)
    return MoEBlock(**base)


def test_moe_routing_covers_tokens_and_respects_capacity():
    layer = _block(capacity_factor=0.5)
    rng = jax.random.PRNGKey(0)
    p = init_moe_block(rng, 16, layer)
    n = 64
    h = jnp.asarray(np.random.RandomState(0).randn(n, 16), jnp.float32)
    gates = jax.nn.softmax(h @ p["router"], axis=-1)
    expert_w = {k: p[k] for k in ("w1", "b1", "w2", "b2")}
    out = moe_dispatch_ffn(layer, expert_w, h, gates, 0, layer.num_experts)
    assert out.shape == (n, 16)
    # tokens over capacity contribute exactly zero (pass-through residual)
    cap = moe_capacity(layer, n)
    top1 = np.asarray(jnp.argmax(gates, axis=-1))
    onehot = np.eye(layer.num_experts)[top1]
    pos = (np.cumsum(onehot, axis=0) * onehot).sum(-1) - 1
    dropped = pos >= cap
    assert dropped.any()  # capacity_factor 0.5 forces drops
    np.testing.assert_array_equal(np.asarray(out)[dropped], 0.0)
    assert np.abs(np.asarray(out)[~dropped]).sum() > 0


@pytest.mark.parametrize("n_shards", [2, 4, 8])
def test_ep_matches_single_device(n_shards):
    layer = _block()
    p = init_moe_block(jax.random.PRNGKey(1), 16, layer)
    x = jnp.asarray(np.random.RandomState(2).randn(4, 12, 16), jnp.float32)
    single = _apply_moe_block(layer, p, x)

    import dataclasses

    spec = TransformerAutoEncoder(**MOE_KW).build_spec(N_TAGS, N_TAGS)
    spec = dataclasses.replace(spec, expert_parallel=n_shards)
    sharded = apply_ep_moe_block(spec, layer, p, x)
    np.testing.assert_allclose(single, sharded, rtol=2e-4, atol=2e-6)


def test_ep_grad_matches_single_device():
    layer = _block()
    p = init_moe_block(jax.random.PRNGKey(3), 16, layer)
    x = jnp.asarray(np.random.RandomState(4).randn(2, 8, 16), jnp.float32)

    import dataclasses

    spec = TransformerAutoEncoder(**MOE_KW).build_spec(N_TAGS, N_TAGS)
    spec = dataclasses.replace(spec, expert_parallel=4)

    g_single = jax.grad(lambda q: jnp.sum(_apply_moe_block(layer, q, x) ** 2))(p)
    g_ep = jax.grad(
        lambda q: jnp.sum(apply_ep_moe_block(spec, layer, q, x) ** 2)
    )(p)
    for a, b in zip(jax.tree_util.tree_leaves(g_single),
                    jax.tree_util.tree_leaves(g_ep)):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=5e-5)


def test_moe_model_trains_and_roundtrips():
    import pickle

    X = np.random.RandomState(5).rand(96, N_TAGS).astype(np.float32)
    np.random.seed(21)
    plain = TransformerAutoEncoder(**MOE_KW)
    plain.fit(X, X)
    assert np.isfinite(plain.history["loss"]).all()
    np.random.seed(21)
    ep = TransformerAutoEncoder(expert_parallel=8, **MOE_KW)
    ep.fit(X, X)
    assert ep_degree(ep.spec_) == 8
    np.testing.assert_allclose(
        plain.history["loss"], ep.history["loss"], rtol=2e-4
    )
    np.testing.assert_allclose(
        plain.predict(X), ep.predict(X), rtol=2e-4, atol=2e-5
    )
    loaded = pickle.loads(pickle.dumps(ep))
    np.testing.assert_allclose(
        ep.predict(X), loaded.predict(X), rtol=2e-4, atol=2e-5
    )


def test_ep_validation():
    with pytest.raises(ValueError, match="divisible"):
        TransformerAutoEncoder(
            expert_parallel=8, **{**MOE_KW, "num_experts": 6}
        ).build_spec(N_TAGS, N_TAGS)
    with pytest.raises(ValueError, match="MoEBlock"):
        TransformerAutoEncoder(
            kind="transformer_model", lookback_window=16, expert_parallel=4
        ).build_spec(N_TAGS, N_TAGS)
    # tp+ep on one spec: rejected (tp's transformer-block requirement
    # fires first in build_spec; prepare_ep_spec's combine check backstops
    # direct spec construction)
    with pytest.raises(ValueError, match="TransformerBlock|cannot combine"):
        TransformerAutoEncoder(
            expert_parallel=2, tensor_parallel=2, **MOE_KW
        ).build_spec(N_TAGS, N_TAGS)
    import dataclasses

    spec = TransformerAutoEncoder(**MOE_KW).build_spec(N_TAGS, N_TAGS)
    with pytest.raises(ValueError, match="cannot combine"):
        prepare_ep_spec(
            dataclasses.replace(spec, expert_parallel=2, pipeline_parallel=2)
        )


def test_ep_machines_take_serial_fallback_and_skip_batcher(monkeypatch):
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel.batch_trainer import _plan_machine
    from gordo_tpu.server import batcher as batcher_mod
    from gordo_tpu.server.batcher import maybe_submit

    config = {
        "name": "ep-machine",
        "dataset": {
            "type": "RandomDataset",
            "tags": [f"ep-tag-{i}" for i in range(N_TAGS)],
            "train_start_date": "2019-01-01T00:00:00+00:00",
            "train_end_date": "2019-01-08T00:00:00+00:00",
        },
        "model": {
            "gordo_tpu.models.models.TransformerAutoEncoder": {
                **{k: v for k, v in MOE_KW.items() if k != "kind"},
                "kind": "moe_transformer_model",
                "expert_parallel": 8,
            }
        },
    }
    machine = Machine.from_config(config, project_name="ep-test")
    assert _plan_machine(machine) is None

    spec = TransformerAutoEncoder(
        expert_parallel=8, **MOE_KW
    ).build_spec(N_TAGS, N_TAGS)
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    monkeypatch.setattr(
        batcher_mod.CrossModelBatcher,
        "submit",
        lambda self, *a: pytest.fail("ep spec reached the batcher queue"),
    )
    assert maybe_submit(spec, None, None) is None


def test_moe_without_ep_rides_the_fleet_vmap_path():
    """Plain MoE machines (expert_parallel off) are batchable like any
    other spec — routing is pure vmappable array math."""
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel.batch_trainer import _plan_machine

    config = {
        "name": "moe-plain",
        "dataset": {
            "type": "RandomDataset",
            "tags": [f"mp-{i}" for i in range(N_TAGS)],
            "train_start_date": "2019-01-01T00:00:00+00:00",
            "train_end_date": "2019-01-08T00:00:00+00:00",
        },
        "model": {
            "gordo_tpu.models.models.TransformerAutoEncoder": {
                **{k: v for k, v in MOE_KW.items() if k != "kind"},
                "kind": "moe_transformer_model",
            }
        },
    }
    machine = Machine.from_config(config, project_name="moe-test")
    assert _plan_machine(machine) is not None


def test_moe_without_ep_batches_across_models():
    """Plain MoE predicts fuse through the cross-model batcher — routing is
    vmappable array math like any other spec."""
    import threading

    from gordo_tpu.server.batcher import CrossModelBatcher

    X = np.random.RandomState(8).rand(64, N_TAGS).astype(np.float32)
    small = {**MOE_KW, "num_blocks": 1, "epochs": 1}
    models = []
    for seed in range(2):
        np.random.seed(seed)
        m = TransformerAutoEncoder(**small)
        m.fit(X, X)
        models.append(m)
    direct = [m.predict(X) for m in models]

    b = CrossModelBatcher(window_ms=20, max_batch=8)
    results = [None] * len(models)

    def run(i):
        results[i] = b.submit(models[i].spec_, models[i].params_, X)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for got, want in zip(results, direct):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert b.stats["largest_batch"] == 2


def test_bf16_compute_keeps_router_decisions_f32():
    """compute_dtype=bfloat16 casts activations/matmuls — but NOT the MoE
    router weights: routing is a decision, and quantizing the router can
    flip top-1 assignments relative to the float32 model."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from gordo_tpu.models.factories.transformer import moe_transformer_model
    from gordo_tpu.ops.nn import apply_model, init_model_params
    from gordo_tpu.models.spec import MoEBlock

    spec = moe_transformer_model(
        n_features=4, lookback_window=8, d_model=16, num_heads=2,
        num_experts=4, expert_dim=16, num_blocks=1,
    )
    params = init_model_params(jax.random.PRNGKey(0), spec)
    # craft a router whose top-2 logit columns differ by LESS than bf16
    # resolution near 1.0 (~0.008): a bf16-cast router would tie them
    moe_i = next(
        i for i, l in enumerate(spec.layers) if isinstance(l, MoEBlock)
    )
    params = list(params)
    p = dict(params[moe_i])
    router = np.zeros(p["router"].shape, np.float32)
    router[:, 0] = 1.0
    router[:, 1] = 1.0001  # f32 argmax -> expert 1; bf16 would tie -> 0
    p["router"] = jnp.asarray(router)
    # make the two experts produce wildly different outputs
    w1 = np.asarray(p["w1"]).copy()
    w1[0] = 0.0
    w1[1] = 100.0
    p["w1"] = jnp.asarray(w1)
    params[moe_i] = p

    x = jnp.asarray(np.random.RandomState(0).rand(2, 8, 4), jnp.float32)
    out_f32, _ = apply_model(spec, params, x)

    spec_bf16 = dataclasses.replace(spec, compute_dtype="bfloat16")
    out_bf16, _ = apply_model(spec_bf16, params, x)
    # same routing => outputs agree to bf16 activation noise; a routing
    # flip to expert 0 (w1=0) would change outputs by orders of magnitude
    ratio = float(
        jnp.linalg.norm(out_bf16.astype(jnp.float32) - out_f32)
        / jnp.linalg.norm(out_f32)
    )
    assert ratio < 0.1, f"routing diverged under bf16 compute (ratio {ratio})"
