"""
Chaos acceptance for the self-healing drift loop (ISSUE 13, tentpole
layer 4): 12 machines serve under live threaded load while 2 of them
receive drifted sensor data. The loop must close end to end — detect
(views -> observability/drift.py), trigger (one deduplicated rebuild
request per drifted machine), rebuild (warm-start delta revision of
EXACTLY the drifted machines), swap (atomic cutover, in-flight requests
unharmed) — with zero 5xx anywhere, zero steady-state trace compiles
after the swap, hysteresis suppressing a second enqueue for the same
episode, and the rebuilt models' drift scores recalibrating to their
new normal.
"""

import json
import os
import threading
import time

import numpy as np
import pytest
import yaml

from gordo_tpu.builder import drift_rebuild
from gordo_tpu.observability import drift
from gordo_tpu.observability import metrics as metric_catalog
from gordo_tpu.parallel import BatchedModelBuilder, drift_queue
from gordo_tpu.server import batcher as batcher_mod
from gordo_tpu.server import build_app, hotswap
from gordo_tpu.server import utils as server_utils
from gordo_tpu.workflow.normalized_config import NormalizedConfig

pytestmark = pytest.mark.chaos

N_MACHINES = 12
DRIFTED = ("dl-0", "dl-1")
PROJECT = "drift-loop"
N_TAGS = 4


def _machine_block(name):
    tags = "".join(f"\n      - {name}-tag-{j}" for j in range(N_TAGS))
    return f"""
  - name: {name}
    dataset:
      tags:{tags}
      target_tag_list:{tags}
      train_start_date: '2019-01-01T00:00:00+00:00'
      train_end_date: '2019-01-03T00:00:00+00:00'
      data_provider: {{type: RandomDataProvider}}
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        require_thresholds: false
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
            - sklearn.preprocessing.MinMaxScaler
            - gordo_tpu.models.models.AutoEncoder:
                kind: feedforward_hourglass
                epochs: 1
"""


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """12 trained machines in a revision dir, registered for warm-start."""
    root = tmp_path_factory.mktemp("drift-loop")
    collection = root / "rev-initial"
    register = root / "register"
    cfg = "machines:" + "".join(
        _machine_block(f"dl-{i}") for i in range(N_MACHINES)
    )
    machines = NormalizedConfig(
        yaml.safe_load(cfg), project_name=PROJECT
    ).machines
    results = BatchedModelBuilder(
        machines,
        output_dir=str(collection),
        model_register_dir=str(register),
    ).build()
    assert len(results) == N_MACHINES
    return {
        "root": str(root),
        "collection": str(collection),
        "register": str(register),
        "queue": str(root / "queue"),
        "machines": machines,
        "names": [m.name for m in machines],
    }


def _payload_variants(rng):
    """Three stable request payloads per machine (±10% input scale) so
    each model's reconstruction-error stream has genuine variance — a
    frozen zero-variance baseline would read float jitter as drift."""
    base = rng.rand(20, N_TAGS)
    return [
        {"X": (base * scale).tolist(), "y": (base * scale).tolist()}
        for scale in (0.9, 1.0, 1.1)
    ]


class _Load:
    """Open-loop-ish threaded load: every machine, strict per-machine
    payload-variant rotation, per-machine revision-header transitions."""

    def __init__(self, app, names):
        self.app = app
        self.names = names
        rng = np.random.RandomState(13)
        self.variants = {name: _payload_variants(rng) for name in names}
        self.counts = {name: 0 for name in names}
        self.revisions = {name: [] for name in names}
        self.status_5xx = 0
        self.requests = 0
        self.lock = threading.Lock()
        self.stop = threading.Event()
        self.threads = []

    def _next(self, tid, i):
        name = self.names[(tid + i) % len(self.names)]
        with self.lock:
            variant = self.variants[name][self.counts[name] % 3]
            self.counts[name] += 1
        return name, variant

    def _run(self, tid):
        client = self.app.test_client()
        i = 0
        while not self.stop.is_set():
            name, variant = self._next(tid, i)
            i += 1
            resp = client.post(
                f"/gordo/v0/{PROJECT}/{name}/anomaly/prediction",
                json=variant,
            )
            revision = resp.headers.get("revision")
            with self.lock:
                self.requests += 1
                if resp.status_code >= 500:
                    self.status_5xx += 1
                seen = self.revisions[name]
                if revision and (not seen or seen[-1] != revision):
                    seen.append(revision)

    def start(self, n=3):
        for tid in range(n):
            thread = threading.Thread(target=self._run, args=(tid,),
                                      daemon=True)
            thread.start()
            self.threads.append(thread)

    def halt(self):
        self.stop.set()
        for thread in self.threads:
            thread.join(timeout=30)


def _wait(predicate, timeout_s, what):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.1)
    pytest.fail(f"timed out after {timeout_s}s waiting for {what}")


def test_self_healing_drift_loop(fleet, monkeypatch):
    monkeypatch.setenv("GORDO_TPU_DRIFT_DETECT", "1")
    monkeypatch.setenv("GORDO_TPU_DRIFT_MIN_SAMPLES", "6")
    monkeypatch.setenv("GORDO_TPU_DRIFT_THRESHOLD", "4.0")
    monkeypatch.setenv("GORDO_TPU_DRIFT_COOLDOWN_S", "3600")
    monkeypatch.setenv("GORDO_TPU_DRIFT_QUEUE_DIR", fleet["queue"])
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setenv("N_CACHED_MODELS", "32")
    monkeypatch.delenv("GORDO_TPU_HOT_SWAP", raising=False)
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    server_utils.clear_model_caches()
    drift.reset()
    hotswap.reset_for_tests()

    app = build_app({"MODEL_COLLECTION_DIR": fleet["collection"]})
    # boot warmup, as production would: params banked + programs AOT
    from gordo_tpu.server.warmup import warmup_collection

    assert warmup_collection(fleet["collection"])["failed"] == []

    load = _Load(app, fleet["names"])
    load.start(n=3)
    try:
        # -------- detect: live traffic seeds every machine's baseline
        _wait(
            lambda: all(
                drift.snapshot().get(n, {}).get("status") == "ok"
                for n in fleet["names"]
            ),
            timeout_s=120,
            what="all 12 baselines to freeze",
        )

        # drifted sensor feed on exactly 2 machines: same serving path,
        # 15x out-of-range inputs — the views' recorded reconstruction
        # error must trip CUSUM and enqueue ONE rebuild per machine
        injector = app.test_client()
        for name in DRIFTED:
            drifted = (np.asarray(load.variants[name][1]["X"]) * 15.0).tolist()
            drifted_payload = {"X": drifted, "y": drifted}
            for _attempt in range(100):
                resp = injector.post(
                    f"/gordo/v0/{PROJECT}/{name}/anomaly/prediction",
                    json=drifted_payload,
                )
                assert resp.status_code < 500
                if drift.snapshot()[name]["status"] == "drifted":
                    break
            else:
                pytest.fail(f"{name} never detected as drifted")

        pending = sorted(
            r["machine"] for r in drift_queue.pending(fleet["queue"])
        )
        assert pending == sorted(DRIFTED)

        # -------- hysteresis: the SAME episode cannot enqueue twice
        events_before = {
            n: drift.snapshot()[n]["events"] for n in DRIFTED
        }
        for name in DRIFTED:
            drifted = (np.asarray(load.variants[name][1]["X"]) * 15.0).tolist()
            drifted_payload = {"X": drifted, "y": drifted}
            for _ in range(5):
                injector.post(
                    f"/gordo/v0/{PROJECT}/{name}/anomaly/prediction",
                    json=drifted_payload,
                )
        assert drift_queue.depth(fleet["queue"]) == len(DRIFTED)
        for name in DRIFTED:
            assert drift.snapshot()[name]["events"] == events_before[name]

        # -------- rebuild: drain into a warm-start delta revision of
        # EXACTLY the drifted machines
        warm_before = metric_catalog.WARM_STARTS.value()
        report = drift_rebuild.drain_drift_queue(
            fleet["machines"],
            fleet["queue"],
            fleet["root"],
            model_register_dir=fleet["register"],
        )
        assert sorted(report["built"]) == sorted(DRIFTED)
        assert report["failed"] == []
        assert report["revision"] is not None
        # warm-start counter: the 2 drifted machines and NOTHING else
        assert metric_catalog.WARM_STARTS.value() - warm_before == 2
        for name in DRIFTED:
            assert metric_catalog.DRIFT_REBUILDS.value(model=name) == 1
        for name in set(fleet["names"]) - set(DRIFTED):
            assert metric_catalog.DRIFT_REBUILDS.value(model=name) == 0
        assert drift_queue.depth(fleet["queue"]) == 0

        # -------- swap: atomic cutover under load
        swapped = hotswap.poll_once(fleet["collection"])
        assert sorted(swapped) == sorted(DRIFTED)
        for name in DRIFTED:
            assert metric_catalog.HOT_SWAPS.value(model=name) == 1
            assert hotswap.active(name) is not None
        time.sleep(0.5)  # let requests in flight at the cutover finish
        compiles_after_swap = metric_catalog.TRACE_COMPILES.value()
        post_swap_floor = load.requests + 3 * len(fleet["names"])
        _wait(
            lambda: load.requests >= post_swap_floor,
            timeout_s=120,
            what="post-swap traffic over every machine",
        )
        # zero steady-state trace compiles after the swap: same spec,
        # same bucket, bank slot replaced in place
        assert metric_catalog.TRACE_COMPILES.value() == compiles_after_swap

        # -------- recalibrate: rebuilt models settle at their NEW normal
        _wait(
            lambda: all(
                drift.snapshot().get(n, {}).get("status") == "ok"
                and drift.snapshot()[n]["events"] == 0
                for n in DRIFTED
            ),
            timeout_s=120,
            what="rebuilt models to recalibrate",
        )
    finally:
        load.halt()

    # -------- zero downtime, correct routing
    assert load.status_5xx == 0, (
        f"{load.status_5xx} 5xx of {load.requests} requests"
    )
    assert load.requests > 0
    for name in fleet["names"]:
        seen = load.revisions[name]
        if name in DRIFTED:
            assert seen[-1] == report["revision"], (name, seen)
            assert seen[0] == "rev-initial"
        else:
            assert seen == ["rev-initial"], (name, seen)

    # the delta revision only holds the drifted machines + the marker
    rev_dir = os.path.join(fleet["root"], report["revision"])
    artifact_dirs = sorted(
        n for n in os.listdir(rev_dir)
        if os.path.isdir(os.path.join(rev_dir, n))
    )
    assert artifact_dirs == sorted(DRIFTED)
    with open(os.path.join(rev_dir, hotswap.COMPLETE_MARKER)) as fh:
        marker = json.load(fh)
    assert marker["machines"] == sorted(DRIFTED)


def test_prewarm_accepts_explicit_revision(fleet, monkeypatch):
    """Satellite: ``POST /debug/prewarm`` warms a named sibling revision
    (the gateway's pre-cutover warm target); unknown revisions are 410
    like the prediction routes."""
    monkeypatch.setenv("GORDO_TPU_DEBUG_ENDPOINTS", "1")
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    app = build_app({"MODEL_COLLECTION_DIR": fleet["collection"]})
    client = app.test_client()

    resp = client.post(
        "/debug/prewarm?machine=dl-0&revision=rev-initial"
    )
    assert resp.status_code == 200
    body = resp.get_json()
    assert body["revision"] == "rev-initial"
    assert body["failed"] == []

    resp = client.post("/debug/prewarm?machine=dl-0&revision=no-such-rev")
    assert resp.status_code == 410

    resp = client.post("/debug/prewarm?machine=dl-0&revision=..%2Fescape")
    assert resp.status_code == 410
