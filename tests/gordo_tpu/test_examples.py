"""
The shipped example must actually run (reference analog: notebooks executed
by tests/test_examples.py with the dataset mocked — here the example already
uses RandomDataProvider, so it runs as-is)."""

import os
import subprocess
import sys

REPO = os.path.join(os.path.dirname(__file__), "..", "..")


def test_local_workflow_example_runs():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # drop accelerator site hooks: the example must run on a clean CPU host
    env["PYTHONPATH"] = ""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "local_workflow.py")],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "full YAML -> build -> serve -> predict loop complete" in proc.stdout


def test_notebook_code_cells_execute():
    """Execute the walkthrough notebook's code cells (reference analog:
    tests/test_examples.py running notebooks via nbconvert)."""
    import json

    path = os.path.join(
        REPO, "examples", "Gordo-TPU-Workflow-High-Level.ipynb"
    )
    nb = json.load(open(path))
    code = "\n\n".join(
        "".join(c["source"]) for c in nb["cells"] if c["cell_type"] == "code"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, "-c", "display = print\n" + code],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
