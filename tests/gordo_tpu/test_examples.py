"""
The shipped examples must actually run (reference analog: notebooks executed
by tests/test_examples.py with the dataset mocked — here the examples already
use RandomDataProvider, so they run as-is)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.join(os.path.dirname(__file__), "..", "..")


def _run_example(script: str, timeout: int) -> str:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # drop accelerator site hooks: examples must run on a clean CPU host
    env["PYTHONPATH"] = ""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script)],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    return proc.stdout


@pytest.mark.parametrize(
    "script,sentinel,timeout",
    [
        (
            "local_workflow.py",
            "full YAML -> build -> serve -> predict loop complete",
            600,
        ),
        ("parallel_axes.py", "all six scaling axes ran from config", 900),
    ],
)
def test_example_runs(script, sentinel, timeout):
    assert sentinel in _run_example(script, timeout)


def test_notebook_code_cells_execute():
    """Execute the walkthrough notebook's code cells (reference analog:
    tests/test_examples.py running notebooks via nbconvert)."""
    import json

    path = os.path.join(
        REPO, "examples", "Gordo-TPU-Workflow-High-Level.ipynb"
    )
    nb = json.load(open(path))
    code = "\n\n".join(
        "".join(c["source"]) for c in nb["cells"] if c["cell_type"] == "code"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO
    proc = subprocess.run(
        [sys.executable, "-c", "display = print\n" + code],
        capture_output=True,
        text=True,
        timeout=600,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
