import numpy as np
import pandas as pd
import pytest
from sklearn.pipeline import Pipeline
from sklearn.preprocessing import MinMaxScaler

from gordo_tpu.models.anomaly.diff import (
    DiffBasedAnomalyDetector,
    DiffBasedKFCVAnomalyDetector,
)
from gordo_tpu.models.models import AutoEncoder


@pytest.fixture(scope="module")
def Xy_frames():
    rng = np.random.RandomState(0)
    index = pd.date_range("2019-01-01", periods=300, freq="10min", tz="UTC")
    X = pd.DataFrame(
        rng.rand(300, 3), columns=["t1", "t2", "t3"], index=index
    )
    return X, X.copy()


def _detector(**kwargs):
    return DiffBasedAnomalyDetector(
        base_estimator=Pipeline(
            [
                ("mm", MinMaxScaler()),
                ("ae", AutoEncoder(kind="feedforward_hourglass", epochs=1)),
            ]
        ),
        **kwargs,
    )


def test_cross_validate_sets_thresholds(Xy_frames):
    X, y = Xy_frames
    det = _detector(require_thresholds=True)
    cv_out = det.cross_validate(X=X, y=y)
    assert "estimator" in cv_out
    assert len(cv_out["estimator"]) == 3
    assert det.feature_thresholds_ is not None
    assert len(det.feature_thresholds_) == 3
    assert isinstance(det.aggregate_threshold_, float)
    assert set(det.aggregate_thresholds_per_fold_) == {"fold-0", "fold-1", "fold-2"}
    assert det.feature_thresholds_per_fold_.shape[0] == 3


def test_anomaly_requires_thresholds(Xy_frames):
    X, y = Xy_frames
    det = _detector(require_thresholds=True)
    det.fit(X, y)
    with pytest.raises(AttributeError):
        det.anomaly(X, y)


def test_anomaly_frame_schema(Xy_frames):
    X, y = Xy_frames
    det = _detector(require_thresholds=False)
    det.cross_validate(X=X, y=y)
    det.fit(X, y)
    frame = det.anomaly(X, y, frequency=pd.Timedelta("10min"))
    top = set(frame.columns.get_level_values(0))
    assert {
        "start",
        "end",
        "model-input",
        "model-output",
        "tag-anomaly-scaled",
        "tag-anomaly-unscaled",
        "total-anomaly-scaled",
        "total-anomaly-unscaled",
        "anomaly-confidence",
        "total-anomaly-confidence",
    } <= top
    assert len(frame) == len(X)
    # start column is isoformat strings
    assert frame[("start", "")].iloc[0].startswith("2019-01-01")


def test_anomaly_smoothed_columns(Xy_frames):
    X, y = Xy_frames
    det = _detector(require_thresholds=False, window=12, smoothing_method="sma")
    det.cross_validate(X=X, y=y)
    det.fit(X, y)
    frame = det.anomaly(X, y)
    top = set(frame.columns.get_level_values(0))
    assert {
        "smooth-tag-anomaly-scaled",
        "smooth-total-anomaly-scaled",
        "smooth-tag-anomaly-unscaled",
        "smooth-total-anomaly-unscaled",
    } <= top
    # smoothed metadata recorded
    md = det.get_metadata()
    assert md["window"] == 12
    assert md["smoothing-method"] == "sma"
    assert "smooth-feature-thresholds" in md


def test_default_smoothing_method_set():
    det = DiffBasedAnomalyDetector(
        base_estimator=AutoEncoder(kind="feedforward_hourglass"), window=10
    )
    assert det.smoothing_method == "smm"


def test_get_metadata_thresholds(Xy_frames):
    X, y = Xy_frames
    det = _detector(require_thresholds=False)
    det.cross_validate(X=X, y=y)
    md = det.get_metadata()
    assert "feature-thresholds" in md
    assert "aggregate-threshold" in md
    assert "feature-thresholds-per-fold" in md


def test_kfcv_detector(Xy_frames):
    X, y = Xy_frames
    det = DiffBasedKFCVAnomalyDetector(
        base_estimator=Pipeline(
            [
                ("mm", MinMaxScaler()),
                ("ae", AutoEncoder(kind="feedforward_hourglass", epochs=1)),
            ]
        ),
        require_thresholds=True,
        window=24,
        threshold_percentile=0.99,
    )
    det.cross_validate(X=X, y=y)
    assert isinstance(det.aggregate_threshold_, float)
    assert len(det.feature_thresholds_) == 3
    det.fit(X, y)
    frame = det.anomaly(X, y)
    assert "total-anomaly-confidence" in frame.columns.get_level_values(0)


def test_scoring_passthrough(Xy_frames):
    X, y = Xy_frames
    det = _detector(require_thresholds=False)
    det.fit(X, y)
    assert isinstance(det.score(X, y), float)


def test_sklearn_clone_returns_detector():
    from sklearn.base import clone

    det = _detector(require_thresholds=False)
    c = clone(det)
    assert isinstance(c, DiffBasedAnomalyDetector)
    assert isinstance(c.base_estimator, Pipeline)


def test_kfcv_rejects_windowed_estimator_clearly():
    """Windowed models can't scatter KFold validation errors per row; the
    detector must say so up front (the reference fails with a bare numpy
    broadcast error instead)."""
    from gordo_tpu import serializer

    model = serializer.from_definition({
        "gordo_tpu.models.anomaly.diff.DiffBasedKFCVAnomalyDetector": {
            "base_estimator": {
                "gordo_tpu.models.models.LSTMAutoEncoder": {
                    "kind": "lstm_symmetric", "dims": [8], "funcs": ["tanh"],
                    "lookback_window": 12, "epochs": 1,
                }
            },
        }
    })
    X = pd.DataFrame(
        np.random.RandomState(0).rand(120, 4),
        index=pd.date_range("2019-01-01", periods=120, freq="10min", tz="UTC"),
        columns=list("abcd"),
    )
    with pytest.raises(ValueError, match="offset-free"):
        model.cross_validate(X=X, y=X)
