"""
Serving resilience layer (server/resilience.py): admission control,
deadlines, circuit breakers, the negative model-load cache, and the device
watchdog — unit-level plus in-process WSGI drives.

Every knob defaults off; each test arms exactly the knob under test via
monkeypatch and resets the process-wide state afterwards.
"""

import json
import pathlib
import threading
import time

import pytest

from gordo_tpu.observability import metrics as metric_catalog
from gordo_tpu.server import resilience
from gordo_tpu.server import utils as server_utils
from gordo_tpu.util import faults


@pytest.fixture(autouse=True)
def _fresh_resilience_state(monkeypatch):
    """Gate counters, breakers, drain flag, fault plan: zeroed per test."""
    monkeypatch.delenv(faults.PLAN_ENV, raising=False)
    faults.reset_plan()
    resilience.reset_for_tests()
    yield
    faults.reset_plan()
    resilience.reset_for_tests()


def _set_plan(monkeypatch, rules):
    monkeypatch.setenv(faults.PLAN_ENV, json.dumps({"rules": rules}))
    faults.reset_plan()


# ---------------------------------------------------------- admission gate
def test_gate_disabled_by_default():
    for _ in range(64):
        assert resilience.try_admit() is None
    assert resilience.gated_inflight() == 64


def test_gate_sheds_past_limit_and_releases(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_MAX_INFLIGHT", "2")
    monkeypatch.setenv("GORDO_TPU_RETRY_AFTER_S", "7")
    before = metric_catalog.SERVER_SHED.value(reason="max_inflight")
    assert resilience.try_admit() is None
    assert resilience.try_admit() is None
    shed = resilience.try_admit()
    assert shed is not None
    assert shed["reason"] == "max_inflight"
    assert shed["retry-after-seconds"] == 7.0
    assert metric_catalog.SERVER_SHED.value(reason="max_inflight") == before + 1
    # a shed holds no slot; a release frees one
    resilience.release()
    assert resilience.try_admit() is None


# --------------------------------------------------------------- deadlines
def test_deadline_scope_and_check(monkeypatch):
    assert resilience.remaining_s() is None  # no scope: no budget
    with resilience.request_scope(model="m", deadline_ms=10_000):
        assert resilience.current_model() == "m"
        remaining = resilience.remaining_s()
        assert remaining is not None and 9 < remaining <= 10
        resilience.check_deadline("preflight")  # plenty left: no raise
    with resilience.request_scope(model="m", deadline_ms=1):
        time.sleep(0.01)
        before = metric_catalog.SERVER_DEADLINE_EXCEEDED.value(
            where="preflight"
        )
        with pytest.raises(resilience.DeadlineExceeded):
            resilience.check_deadline("preflight")
        assert (
            metric_catalog.SERVER_DEADLINE_EXCEEDED.value(where="preflight")
            == before + 1
        )
    assert resilience.current_model() is None  # scope restored


def test_deadline_header_beats_env_default(monkeypatch):
    assert resilience.deadline_ms_from({}) is None
    monkeypatch.setenv("GORDO_TPU_DEADLINE_MS", "500")
    assert resilience.deadline_ms_from({}) == 500.0
    assert (
        resilience.deadline_ms_from({"X-Gordo-Deadline-Ms": "125"}) == 125.0
    )
    # malformed values are ignored (not a 400): falls back to nothing
    monkeypatch.delenv("GORDO_TPU_DEADLINE_MS")
    assert resilience.deadline_ms_from({"X-Gordo-Deadline-Ms": "soon"}) is None
    assert resilience.deadline_ms_from({"X-Gordo-Deadline-Ms": "-5"}) is None


# ---------------------------------------------------------- circuit breaker
def test_breaker_disabled_without_threshold():
    assert resilience.breaker_for("any-model") is None


def test_breaker_opens_after_consecutive_transient_failures(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("GORDO_TPU_BREAKER_COOLDOWN_S", "60")
    breaker = resilience.breaker_for("m-a")
    for _ in range(2):
        breaker.record_failure(faults.TransientFault("hiccup"))
        assert breaker.allow() is None  # still closed
    breaker.record_failure(faults.TransientFault("hiccup"))
    info = breaker.allow()
    assert info is not None and info["model"] == "m-a"
    assert 0 < info["retry-after-seconds"] <= 60
    assert breaker.state == resilience.OPEN
    assert metric_catalog.BREAKER_STATE.value(model="m-a") == resilience.OPEN
    # a success between failures resets the consecutive count
    breaker2 = resilience.breaker_for("m-b")
    breaker2.record_failure(faults.TransientFault("x"))
    breaker2.record_failure(faults.TransientFault("x"))
    breaker2.record_success()
    breaker2.record_failure(faults.TransientFault("x"))
    assert breaker2.state == resilience.CLOSED


def test_breaker_permanent_fault_opens_immediately(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_BREAKER_THRESHOLD", "5")
    breaker = resilience.breaker_for("m-c")
    breaker.record_failure(faults.NonFiniteDataError("poisoned output"))
    assert breaker.state == resilience.OPEN


def test_breaker_half_open_probe_closes_or_reopens(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("GORDO_TPU_BREAKER_COOLDOWN_S", "0.05")
    breaker = resilience.breaker_for("m-d")
    breaker.record_failure(faults.PermanentFault("corrupt"))
    assert breaker.allow() is not None  # open, cooling down
    time.sleep(0.06)
    assert breaker.allow() is None  # half-open: this caller is the probe
    assert breaker.state == resilience.HALF_OPEN
    # concurrent request during the probe still fast-fails
    assert breaker.allow() is not None
    breaker.record_failure(faults.PermanentFault("still corrupt"))
    assert breaker.state == resilience.OPEN
    time.sleep(0.06)
    assert breaker.allow() is None
    breaker.record_success()
    assert breaker.state == resilience.CLOSED
    assert breaker.allow() is None


def test_breaker_half_open_admits_exactly_one_probe_under_race(monkeypatch):
    """N threads hit allow() at the same instant on a cooled-down breaker:
    exactly one is admitted as the probe, every loser gets the fast-fail
    dict (with retry-after) without touching the model."""
    monkeypatch.setenv("GORDO_TPU_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("GORDO_TPU_BREAKER_COOLDOWN_S", "30")
    breaker = resilience.breaker_for("m-race")
    for round_no in range(3):  # repeat: the race must lose every time
        breaker.record_failure(faults.PermanentFault("corrupt"))
        assert breaker.state == resilience.OPEN
        breaker._opened_at -= 31  # cooldown elapsed, about to half-open
        n = 32
        barrier = threading.Barrier(n)
        results = [None] * n

        def hit(i):
            barrier.wait()
            results[i] = breaker.allow()

        threads = [threading.Thread(target=hit, args=(i,)) for i in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        probes = [r for r in results if r is None]
        rejected = [r for r in results if r is not None]
        assert len(probes) == 1, f"round {round_no}: {len(probes)} probes admitted"
        assert len(rejected) == n - 1
        assert all("retry-after-seconds" in r for r in rejected)
        assert breaker.state == resilience.HALF_OPEN
        # loop back: the probe reports failure, breaker re-opens


def test_breaker_lost_probe_does_not_wedge_half_open(monkeypatch):
    """A probe whose thread dies without record_success/record_failure must
    not leave the breaker rejecting everyone forever: after a further
    cooldown the probe lease expires and one replacement is admitted."""
    monkeypatch.setenv("GORDO_TPU_BREAKER_THRESHOLD", "1")
    monkeypatch.setenv("GORDO_TPU_BREAKER_COOLDOWN_S", "30")
    breaker = resilience.breaker_for("m-lost")
    breaker.record_failure(faults.PermanentFault("corrupt"))
    breaker._opened_at -= 31
    assert breaker.allow() is None  # probe admitted ... and then lost
    assert breaker.allow() is not None  # others still fast-fail
    # less than a cooldown later: still just the one outstanding probe
    breaker._probe_started_at -= 15
    assert breaker.allow() is not None
    # a full cooldown after the probe started: lease expired, one (and
    # only one) replacement probe goes through
    breaker._probe_started_at -= 16
    assert breaker.allow() is None
    assert breaker.allow() is not None
    # the replacement reporting back settles the breaker normally
    breaker.record_success()
    assert breaker.state == resilience.CLOSED
    assert breaker.allow() is None


# ------------------------------------------------------------ output guard
def test_output_guard_off_by_default():
    import numpy as np

    resilience.check_output_finite(np.array([1.0, float("nan")]), "m")


def test_output_guard_raises_when_enabled(monkeypatch):
    import numpy as np

    monkeypatch.setenv("GORDO_TPU_VALIDATE_OUTPUT", "1")
    resilience.check_output_finite(np.ones(4), "m")
    with pytest.raises(faults.NonFiniteDataError, match="'m'"):
        resilience.check_output_finite(np.array([1.0, float("inf")]), "m")


# -------------------------------------------------------- device watchdog
class _FakeBatcher:
    def __init__(self, stuck):
        self._stuck = stuck

    def device_call_stuck_s(self):
        return self._stuck


def test_watchdog_flags_stuck_dispatcher(monkeypatch):
    import gordo_tpu.server.batcher as batcher_mod

    assert resilience.stuck_device_call_s() is None  # knob unset: off
    monkeypatch.setenv("GORDO_TPU_WATCHDOG_S", "0.5")
    monkeypatch.setattr(batcher_mod, "_batcher", _FakeBatcher(0.1))
    assert resilience.stuck_device_call_s() is None  # busy but under limit
    before = metric_catalog.WATCHDOG_TRIPS.value()
    monkeypatch.setattr(batcher_mod, "_batcher", _FakeBatcher(1.2))
    assert resilience.stuck_device_call_s() == pytest.approx(1.2)
    assert metric_catalog.WATCHDOG_TRIPS.value() == before + 1


# ------------------------------------------------------------------- drain
def test_drain_waits_for_inflight():
    assert resilience.begin_drain() is True
    assert resilience.begin_drain() is False  # only the first caller wins
    assert resilience.is_draining()
    resilience.request_started()
    done = []

    def finish_later():
        time.sleep(0.15)
        resilience.request_finished()
        done.append(True)

    threading.Thread(target=finish_later).start()
    assert resilience.wait_drained(budget_s=5.0) is True
    assert done == [True]


def test_drain_budget_bounds_the_wait():
    resilience.request_started()  # never finished
    t0 = time.monotonic()
    assert resilience.wait_drained(budget_s=0.2) is False
    assert time.monotonic() - t0 < 2.0


# ------------------------------------- model load: negative cache + dogpile
def _write_corrupt_model(tmp_path, name):
    mdir = tmp_path / name
    mdir.mkdir()
    (mdir / "metadata.json").write_text(json.dumps({"dataset": {"tags": []}}))
    (mdir / "model.pkl").write_bytes(b"\x80\x04 truncated garbage")
    return str(tmp_path)


def test_load_failure_is_negative_cached(tmp_path, monkeypatch):
    directory = _write_corrupt_model(tmp_path, "m-corrupt")
    server_utils.clear_model_caches()
    calls = []
    real_load = server_utils.serializer.load

    def counting_load(path):
        calls.append(path)
        return real_load(path)

    monkeypatch.setattr(server_utils.serializer, "load", counting_load)
    fresh_before = metric_catalog.MODEL_LOAD_FAILURES.value(kind="fresh")
    cached_before = metric_catalog.MODEL_LOAD_FAILURES.value(kind="cached")
    with pytest.raises(Exception) as first:
        server_utils.load_model(directory, "m-corrupt")
    # within the TTL the cached failure answers without re-deserializing
    with pytest.raises(Exception) as second:
        server_utils.load_model(directory, "m-corrupt")
    assert len(calls) == 1
    assert second.value is first.value
    assert (
        metric_catalog.MODEL_LOAD_FAILURES.value(kind="fresh")
        == fresh_before + 1
    )
    assert (
        metric_catalog.MODEL_LOAD_FAILURES.value(kind="cached")
        == cached_before + 1
    )
    server_utils.clear_model_caches()


def test_load_failure_ttl_zero_disables_negative_cache(tmp_path, monkeypatch):
    directory = _write_corrupt_model(tmp_path, "m-corrupt2")
    monkeypatch.setenv("GORDO_TPU_LOAD_FAILURE_TTL_S", "0")
    server_utils.clear_model_caches()
    calls = []
    real_load = server_utils.serializer.load

    def counting_load(path):
        calls.append(path)
        return real_load(path)

    monkeypatch.setattr(server_utils.serializer, "load", counting_load)
    for _ in range(2):
        with pytest.raises(Exception):
            server_utils.load_model(directory, "m-corrupt2")
    assert len(calls) == 2  # every request re-reads, the old behavior
    server_utils.clear_model_caches()


def test_missing_model_is_not_negative_cached(tmp_path):
    server_utils.clear_model_caches()
    with pytest.raises(FileNotFoundError):
        server_utils.load_model(str(tmp_path), "not-there")
    # the model appears (rollover in progress) and must serve immediately:
    # the miss was NOT cached, so the next load re-checks the filesystem
    with pytest.raises(FileNotFoundError):
        server_utils.load_model(str(tmp_path), "not-there")
    server_utils.clear_model_caches()


def test_dogpile_lock_single_deserialize(tmp_path, monkeypatch):
    """N threads asking for one uncached model trigger ONE deserialize."""
    server_utils.clear_model_caches()
    calls = []

    def slow_load(path):
        calls.append(path)
        time.sleep(0.1)
        return {"model": path}

    monkeypatch.setattr(server_utils.serializer, "load", slow_load)
    results = []
    threads = [
        threading.Thread(
            target=lambda: results.append(
                server_utils.load_model(str(tmp_path), "m-big")
            )
        )
        for _ in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(calls) == 1
    assert len(results) == 6
    assert all(r is results[0] for r in results)
    server_utils.clear_model_caches()


def test_injected_load_fault_counts_and_caches(tmp_path, monkeypatch):
    """The serve_model_load fault site fails a load deterministically and
    the failure is negative-cached like a real one."""
    _set_plan(
        monkeypatch,
        [{"site": "serve_model_load", "machine": "m-x", "times": 1,
          "error": "permanent"}],
    )
    server_utils.clear_model_caches()
    with pytest.raises(faults.PermanentFault):
        server_utils.load_model(str(tmp_path), "m-x")
    # rule exhausted (times=1) — but the negative cache still answers
    with pytest.raises(faults.PermanentFault):
        server_utils.load_model(str(tmp_path), "m-x")
    server_utils.clear_model_caches()


# ----------------------------------------------- WSGI drives (no models)
@pytest.fixture()
def app(tmp_path):
    from gordo_tpu.server.server import build_app

    server_utils.clear_model_caches()
    collection = tmp_path / "rev-1"
    collection.mkdir()
    return build_app({"MODEL_COLLECTION_DIR": str(collection)})


def test_shed_e2e_503_with_retry_after(app, monkeypatch):
    """One request wedged inside the gated section + MAX_INFLIGHT=1: the
    concurrent request is shed with 503 + Retry-After, and the gate frees
    once the wedged request finishes."""
    monkeypatch.setenv("GORDO_TPU_MAX_INFLIGHT", "1")
    monkeypatch.setenv("GORDO_TPU_RETRY_AFTER_S", "3")
    _set_plan(
        monkeypatch,
        [{"site": "serve_model_load", "times": 1, "error": "wedge",
          "seconds": 0.8}],
    )
    url = "/gordo/v0/p/some-model/prediction"
    statuses = {}

    def wedged():
        # the wedge fires inside load_model; the request then 404s (no
        # such model) — what matters is that it HOLDS its gate slot
        statuses["wedged"] = app.test_client().post(url, json={}).status_code

    t = threading.Thread(target=wedged)
    t.start()
    time.sleep(0.3)  # the wedged request is inside the gated section
    resp = app.test_client().post(url, json={})
    assert resp.status_code == 503
    assert resp.headers["Retry-After"] == "3"
    body = resp.get_json()
    assert body["reason"] == "max_inflight"
    t.join()
    assert statuses["wedged"] == 404
    # slot released: the next request is admitted (404, not 503)
    assert app.test_client().post(url, json={}).status_code == 404


def test_breaker_e2e_corrupt_artifact_fast_fails(app, tmp_path, monkeypatch):
    """A corrupt artifact: first request pays the load failure (500),
    the breaker opens (permanent-class), and subsequent requests fast-fail
    503 naming the model — without re-reading the artifact every time."""
    monkeypatch.setenv("GORDO_TPU_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("GORDO_TPU_BREAKER_COOLDOWN_S", "60")
    collection = app.config["MODEL_COLLECTION_DIR"]
    _write_corrupt_model(pathlib.Path(collection), "m-bad")
    url = "/gordo/v0/p/m-bad/prediction"
    client = app.test_client()
    resp = client.post(url, json={})
    assert resp.status_code == 500
    assert "failed to load" in resp.get_json()["error"]
    resp = client.post(url, json={})
    assert resp.status_code == 503
    body = resp.get_json()
    assert body["model"] == "m-bad"
    assert "retry-after-seconds" in body
    assert int(resp.headers["Retry-After"]) >= 0
    assert (
        resilience.breaker_for("m-bad").state == resilience.OPEN
    )


def test_deadline_e2e_504(monkeypatch, model_collection_directory,
                          trained_model_directories, gordo_project,
                          gordo_name, X_payload):
    """A wedged predict + a small deadline header: 504, not a hang."""
    from gordo_tpu.server.server import build_app
    from gordo_tpu.server.utils import dataframe_to_dict

    server_utils.clear_model_caches()
    app = build_app({"MODEL_COLLECTION_DIR": model_collection_directory})
    _set_plan(
        monkeypatch,
        [{"site": "serve_predict", "times": 1, "error": "wedge",
          "seconds": 0.4}],
    )
    url = f"/gordo/v0/{gordo_project}/{gordo_name}/prediction"
    before = metric_catalog.SERVER_DEADLINE_EXCEEDED.value(where="preflight")
    resp = app.test_client().post(
        url,
        json={"X": dataframe_to_dict(X_payload)},
        headers={"X-Gordo-Deadline-Ms": "100"},
    )
    assert resp.status_code == 504
    assert "deadline" in resp.get_json()["error"].lower()
    assert (
        metric_catalog.SERVER_DEADLINE_EXCEEDED.value(where="preflight")
        == before + 1
    )
    # without the header the same route still serves
    resp = app.test_client().post(url, json={"X": dataframe_to_dict(X_payload)})
    assert resp.status_code == 200
