"""
Native C++ kernels: numerical parity with the pandas operations they replace.

The contract is exact equality of semantics with
``Series.resample(freq).agg(method)`` (left-closed/left-labeled buckets,
start_day origin, skipna) and ``Series.rolling(w).min().max()``.
"""

import numpy as np
import pandas as pd
import pytest

from gordo_tpu import native
from gordo_tpu.dataset.datasets import TimeSeriesDataset

pytestmark = pytest.mark.skipif(
    # available() is async on a cold cache; force the build for the suite
    not (native.prebuild(block=True) and native.available()),
    reason="native library unavailable (no g++?)",
)


def _random_series(rng, n, freq_s=60, irregular=True, nan_frac=0.1, tz="UTC"):
    base = pd.Timestamp("2019-01-01T00:07:00", tz=tz)
    if irregular:
        deltas = np.cumsum(rng.randint(1, 3 * freq_s, size=n))
    else:
        deltas = np.arange(n) * freq_s
    index = base + pd.to_timedelta(deltas, unit="s")
    values = rng.randn(n)
    if nan_frac:
        values[rng.rand(n) < nan_frac] = np.nan
    return pd.Series(values, index=index)


@pytest.mark.parametrize("method", ["mean", "min", "max", "sum", "count", "median"])
@pytest.mark.parametrize("irregular", [True, False])
def test_resample_matches_pandas(method, irregular):
    rng = np.random.RandomState(hash(method) % 2**31)
    series = _random_series(rng, 500, irregular=irregular)
    expected = series.resample("10min").agg(method)

    bucket = pd.tseries.frequencies.to_offset("10min").nanos
    ts_ns = series.index.as_unit("ns").asi8
    day_ns = 86_400_000_000_000
    origin = ts_ns[0] - (ts_ns[0] % day_ns)
    first = (ts_ns[0] - origin) // bucket
    last = (ts_ns[-1] - origin) // bucket
    n_buckets = int(last - first + 1)
    origin_ns = int(origin + first * bucket)

    out = native.resample(
        ts_ns, series.to_numpy(np.float64), origin_ns, bucket, n_buckets, [method]
    )[0]
    assert len(out) == len(expected)
    np.testing.assert_allclose(out, expected.to_numpy(np.float64), equal_nan=True)
    # bucket labels line up too
    assert int(expected.index.as_unit("ns").asi8[0]) == origin_ns


def test_resample_multi_agg_single_pass():
    rng = np.random.RandomState(0)
    series = _random_series(rng, 300)
    methods = ["mean", "max", "count"]
    expected = series.resample("10min").agg(methods)

    bucket = pd.tseries.frequencies.to_offset("10min").nanos
    ts_ns = series.index.as_unit("ns").asi8
    day_ns = 86_400_000_000_000
    origin = ts_ns[0] - (ts_ns[0] % day_ns)
    first = (ts_ns[0] - origin) // bucket
    n_buckets = int((ts_ns[-1] - origin) // bucket - first + 1)
    out = native.resample(
        ts_ns,
        series.to_numpy(np.float64),
        int(origin + first * bucket),
        bucket,
        n_buckets,
        methods,
    )
    for i, m in enumerate(methods):
        np.testing.assert_allclose(
            out[i], expected[m].to_numpy(np.float64), equal_nan=True
        )


@pytest.mark.parametrize("w", [1, 6, 50, 144])
def test_rolling_min_max_matches_pandas(w):
    rng = np.random.RandomState(w)
    for n in [w - 1, w, w + 1, 500]:
        if n <= 0:
            continue
        vals = rng.randn(n)
        vals[rng.rand(n) < 0.05] = np.nan
        expected = pd.Series(vals).rolling(w).min().max()
        got = native.rolling_min_max(vals, w)
        if np.isnan(expected):
            assert np.isnan(got)
        else:
            assert np.isclose(got, expected)


def test_dataset_native_path_matches_pandas_path(monkeypatch):
    """TimeSeriesDataset output must be identical with the native resampler
    on and off."""
    cfg = dict(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-04T00:00:00+00:00",
        tags=["native-a", "native-b"],
        data_provider={"type": "RandomDataProvider"},
    )
    X_native, y_native = TimeSeriesDataset(**cfg).get_data()

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_failed", True)
    X_pandas, y_pandas = TimeSeriesDataset(**cfg).get_data()

    pd.testing.assert_frame_equal(X_native, X_pandas)
    pd.testing.assert_frame_equal(y_native, y_pandas)


def test_dataset_native_path_multi_agg(monkeypatch):
    cfg = dict(
        train_start_date="2019-01-01T00:00:00+00:00",
        train_end_date="2019-01-03T00:00:00+00:00",
        tags=["nm-a"],
        aggregation_methods=["mean", "max", "count"],
        data_provider={"type": "RandomDataProvider"},
    )
    X_native, _ = TimeSeriesDataset(**cfg).get_data()
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_failed", True)
    X_pandas, _ = TimeSeriesDataset(**cfg).get_data()
    # exact parity including the int64 dtype of count columns
    pd.testing.assert_frame_equal(X_native, X_pandas)


def test_no_native_env_kill_switch(monkeypatch):
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_load_failed", False)
    monkeypatch.setenv("GORDO_TPU_NO_NATIVE", "1")
    assert not native.available()
    # reset for other tests
    monkeypatch.delenv("GORDO_TPU_NO_NATIVE")
    monkeypatch.setattr(native, "_load_failed", False)


def test_resample_rejects_length_mismatch():
    """Mismatched timestamp/value arrays must raise in Python — the C
    kernel would read out of bounds."""
    ts = np.arange(10, dtype=np.int64) * 600_000_000_000
    vals = np.ones(8)
    with pytest.raises(ValueError, match="length mismatch"):
        native.resample(ts, vals, origin_ns=0, bucket_ns=600_000_000_000,
                        n_buckets=10, methods=["mean"])


# ---------------------------------------------- builder-thread lifecycle
def _fresh_builder_state(monkeypatch, tmp_path):
    from gordo_tpu import native

    monkeypatch.setenv("GORDO_TPU_NATIVE_CACHE", str(tmp_path))
    monkeypatch.delenv("GORDO_TPU_NO_NATIVE", raising=False)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_encode_tpl_fn", None)
    monkeypatch.setattr(native, "_load_failed", False)
    monkeypatch.setattr(native, "_builder_thread", None)
    monkeypatch.setattr(native, "_so_path_cache", str(tmp_path / "stub.so"))
    return native


def test_prebuild_joins_inflight_builder_without_second_compile(
    monkeypatch, tmp_path
):
    """prebuild(block=True) after a non-blocking available() already
    started the builder must join THAT build — never kick a second
    compile of the same artifact."""
    import threading
    import time

    native = _fresh_builder_state(monkeypatch, tmp_path)
    builds = []
    release = threading.Event()

    def counting_build():
        builds.append(1)
        release.wait(timeout=30)
        return None

    monkeypatch.setattr(native, "_build", counting_build)
    assert native.available() is False  # non-blocking: starts the builder
    first = native._builder_thread
    assert first is not None and first.is_alive()

    results = []
    joiner = threading.Thread(
        target=lambda: results.append(native.prebuild(block=True))
    )
    joiner.start()
    deadline = time.monotonic() + 5
    while joiner.is_alive() and time.monotonic() < deadline and not builds:
        time.sleep(0.01)
    # the blocking prebuild is waiting on the ORIGINAL builder
    assert native._builder_thread is first
    assert len(builds) == 1
    release.set()
    joiner.join(timeout=10)
    assert results == [False]  # the stubbed build produced no artifact
    assert len(builds) == 1, "prebuild spawned a second compile"
    assert native._load_failed is True


def test_crashed_builder_is_restarted_but_clean_failure_latches(
    monkeypatch, tmp_path
):
    """A builder that died by exception (no artifact, no latch) is
    replaced on the next request; a clean build failure latches and is
    never retried."""
    native = _fresh_builder_state(monkeypatch, tmp_path)
    builds = []

    def crashing_build():
        builds.append(1)
        raise RuntimeError("boom")

    monkeypatch.setattr(native, "_build", crashing_build)
    assert native.available() is False
    native._builder_thread.join(timeout=10)
    assert native._load_failed is False  # crash leaves the latch open
    assert len(builds) == 1

    # next blocking prebuild retries with a fresh builder...
    monkeypatch.setattr(native, "_build", lambda: builds.append(1) or None)
    assert native.prebuild(block=True) is False
    assert len(builds) == 2
    assert native._load_failed is True  # ...whose clean failure latches

    # latched: further prebuilds neither restart nor compile again
    assert native.prebuild(block=True) is False
    assert len(builds) == 2
