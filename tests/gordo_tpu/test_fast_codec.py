"""
Codec-parity suite for the serving fast path (server/fast_codec.py).

The contract: with `GORDO_TPU_FAST_CODEC` on (the default), every response
the fast path produces is BYTE-IDENTICAL to what the pandas path would
have produced, and every payload the fast path cannot handle falls back to
the pandas path (counted, never erred). Golden payloads cover the
canonical shapes (rect list, column dict), the fallback shapes
(multi-level, ragged, non-numeric), and the value edge cases (NaN/Inf,
string index, int columns).
"""

import json
import re

import numpy as np
import pandas as pd
import pytest

from gordo_tpu.observability import metrics as metric_catalog
from gordo_tpu.server import fast_codec
from gordo_tpu.server.utils import dataframe_from_dict, dataframe_to_dict
from gordo_tpu.server.views import json_serializer_default
from gordo_tpu.util import _simplejson as simplejson


def _slow_json(df: pd.DataFrame) -> str:
    """What the pandas path serializes for one frame."""
    return simplejson.dumps(
        dataframe_to_dict(df), ignore_nan=True, default=json_serializer_default
    )


def _assert_encode_parity(df: pd.DataFrame):
    fragment = fast_codec.encode_dataframe(df)
    assert fragment is not None, "expected the fast path to handle this frame"
    assert fragment == _slow_json(df)


def _response_frame(index, n_tags=3, with_nan=False) -> pd.DataFrame:
    """A canonical response-shaped frame: object start/end columns plus a
    float block under a MultiIndex (models/utils.assemble_multiindex_frame
    layout)."""
    n = len(index)
    rng = np.random.RandomState(0)
    tuples = [("start", ""), ("end", "")]
    tuples += [("model-input", f"t-{i}") for i in range(n_tags)]
    tuples += [("model-output", f"t-{i}") for i in range(n_tags)]
    tuples += [("total-anomaly-scaled", "")]
    num = rng.rand(n, len(tuples) - 2)
    if with_nan:
        num[0, 0] = np.nan
        num[-1, -1] = np.inf
        num[n // 2, 1] = -np.inf
    if isinstance(index, pd.DatetimeIndex):
        start = [ts.isoformat() for ts in index]
        end = [ts.isoformat() for ts in index + pd.Timedelta("10min")]
    else:
        start = [None] * n
        end = [None] * n
    time_block = pd.DataFrame({0: start, 1: end}, index=index, dtype=object)
    numeric = pd.DataFrame(num, index=index)
    numeric.columns = pd.RangeIndex(2, 2 + numeric.shape[1])
    frame = pd.concat((time_block, numeric), axis=1, copy=False)
    frame.columns = pd.MultiIndex.from_tuples(tuples)
    return frame


# ------------------------------------------------------------ encode parity
def test_encode_parity_response_frame_int_index():
    _assert_encode_parity(_response_frame(pd.RangeIndex(50)))


def test_encode_parity_response_frame_datetime_index():
    idx = pd.date_range("2020-01-01", periods=24, freq="10min", tz="UTC")
    _assert_encode_parity(_response_frame(idx))


def test_encode_parity_nan_and_inf_become_null():
    frame = _response_frame(pd.RangeIndex(9), with_nan=True)
    fragment = fast_codec.encode_dataframe(frame)
    assert fragment == _slow_json(frame)
    assert "null" in fragment
    assert "NaN" not in fragment and "Infinity" not in fragment


def test_encode_parity_single_level_columns():
    df = pd.DataFrame(
        np.random.RandomState(1).rand(20, 3),
        columns=["a", "b", "c"],
        index=pd.RangeIndex(20),
    )
    _assert_encode_parity(df)


def test_encode_parity_string_index():
    df = pd.DataFrame(
        np.random.RandomState(2).rand(5, 2),
        columns=["x", "y"],
        index=[f'k-{i}"quote' for i in range(5)],  # escaping must match
    )
    _assert_encode_parity(df)


def test_encode_parity_int_and_bool_columns():
    df = pd.DataFrame(
        {
            "ints": np.arange(7, dtype=np.int64),
            "floats": np.linspace(0, 1, 7),
            "flags": np.arange(7) % 2 == 0,
        }
    )
    _assert_encode_parity(df)


def test_encode_parity_doctest_frame():
    # the dataframe_to_dict doctest frame: MultiIndex + int64 + DatetimeIndex
    columns = pd.MultiIndex.from_tuples(
        (f"feature{i}", f"sub-feature-{ii}") for i in range(2) for ii in range(2)
    )
    index = pd.date_range("2019-01-01", "2019-02-01", periods=2)
    df = pd.DataFrame(np.arange(8).reshape((2, 4)), columns=columns, index=index)
    _assert_encode_parity(df)


def test_encode_fallback_shapes():
    # frames the fast path must refuse (pandas path handles them)
    dup_index = pd.DataFrame({"a": [1.0, 2.0]}, index=[0, 0])
    assert fast_codec.encode_dataframe(dup_index) is None
    empty = pd.DataFrame({"a": []})
    assert fast_codec.encode_dataframe(empty) is None
    datetime_col = pd.DataFrame(
        {"ts": pd.date_range("2020-01-01", periods=3)}
    )
    assert fast_codec.encode_dataframe(datetime_col) is None
    objects = pd.DataFrame({"o": [object(), object()]})
    assert fast_codec.encode_dataframe(objects) is None
    # non-contiguous top-level groups merge in the dict path — the fast
    # encoder builds the nested dict with the same setdefault idiom, so
    # it merges identically instead of bailing
    scattered = pd.DataFrame(
        np.random.rand(3, 3),
        columns=pd.MultiIndex.from_tuples([("a", "x"), ("b", "x"), ("a", "y")]),
    )
    _assert_encode_parity(scattered)


def _raw_frame(index, with_nan=False):
    from gordo_tpu.models import utils as model_utils

    n = len(index)
    rng = np.random.RandomState(5)
    out = rng.rand(n, 2).astype(np.float32)  # model output is float32
    if with_nan:
        out[0, 0] = np.nan
        out[-1, -1] = np.inf
    groups = [
        ("model-input", ["a", "b"], rng.rand(n, 2)),
        ("model-output", ["a", "b"], out),
        ("smooth-total-anomaly-scaled", ("",), rng.rand(n, 1)),
        ("total-anomaly-scaled", ("",), rng.rand(n, 1)),
    ]
    return model_utils.RawFrame(groups, index, pd.Timedelta("10min"))


@pytest.mark.parametrize("with_nan", [False, True])
def test_encode_raw_matches_assembled(with_nan):
    """encode_raw off the unassembled blocks == encode_dataframe of the
    assembled frame == the pandas dict path, byte for byte."""
    for index in (
        pd.RangeIndex(10),
        pd.date_range("2020-01-01", periods=10, freq="10min", tz="UTC"),
    ):
        raw = _raw_frame(index, with_nan=with_nan)
        fragment = fast_codec.encode_raw(raw)
        assert fragment is not None
        assert fragment == fast_codec.encode_dataframe(raw.to_pandas())
        assert fragment == _slow_json(raw.to_pandas())


def test_encode_raw_drop_top_level_matches_pandas_drop():
    raw = _raw_frame(pd.RangeIndex(6))
    dropped = raw.drop_top_level(["smooth-total-anomaly-scaled"])
    df = raw.to_pandas().drop(columns=["smooth-total-anomaly-scaled"], level=0)
    assert fast_codec.encode_raw(dropped) == fast_codec.encode_dataframe(df)
    assert dropped.top_levels() == [
        "model-input",
        "model-output",
        "total-anomaly-scaled",
    ]


def test_splice_response_body():
    assert (
        fast_codec.splice_response_body('{"k": 1}', '{"revision": "r"}')
        == '{"data": {"k": 1}, "revision": "r"}'
    )
    assert fast_codec.splice_response_body('{"k": 1}', "{}") == '{"data": {"k": 1}}'


# ------------------------------------------------------------ decode parity
def _assert_decode_parity(payload):
    fast = fast_codec.decode_dataframe(payload)
    assert fast is not None, "expected the fast path to handle this payload"
    slow = dataframe_from_dict(payload)
    np.testing.assert_array_equal(fast.to_numpy(), slow.to_numpy())
    assert list(fast.index) == list(slow.index)
    assert [str(c) for c in fast.columns] == [str(c) for c in slow.columns]
    # the serialized keys — what the client sees — must agree exactly
    assert list(map(str, fast_codec._index_keys(fast.index))) == list(
        map(str, fast_codec._index_keys(slow.index))
    )


def test_decode_parity_rect_list():
    payload = np.random.RandomState(0).rand(30, 4).tolist()
    _assert_decode_parity(payload)


def test_decode_parity_rect_list_with_nulls():
    payload = [[1.0, None, 3.0], [None, 5.0, 6.0]]
    fast = fast_codec.decode_dataframe(payload)
    slow = dataframe_from_dict(payload)
    np.testing.assert_array_equal(fast.to_numpy(), slow.to_numpy())


def test_decode_parity_column_dict_int_keys():
    df = pd.DataFrame(
        np.random.RandomState(3).rand(12, 3), columns=["a", "b", "c"]
    )
    payload = json.loads(json.dumps(dataframe_to_dict(df)))
    _assert_decode_parity(payload)


def test_decode_parity_column_dict_datetime_keys():
    idx = pd.date_range("2020-01-01", periods=8, freq="10min", tz="UTC")
    df = pd.DataFrame(
        np.random.RandomState(4).rand(8, 2), columns=["a", "b"], index=idx
    )
    payload = json.loads(json.dumps(dataframe_to_dict(df)))
    _assert_decode_parity(payload)


def test_decode_unsorted_keys_sorted_like_pandas():
    payload = {
        "a": {"2": 3.0, "0": 1.0, "1": 2.0},
        "b": {"2": 30.0, "0": 10.0, "1": 20.0},
    }
    _assert_decode_parity(payload)


def test_decode_fallback_shapes():
    # multi-level payload (dict of dict of dicts)
    assert (
        fast_codec.decode_dataframe({"top": {"sub": {"0": 1.0}}}) is None
    )
    # ragged columns
    assert (
        fast_codec.decode_dataframe({"a": {"0": 1.0}, "b": {"0": 1.0, "1": 2.0}})
        is None
    )
    # reordered keys across columns
    assert (
        fast_codec.decode_dataframe(
            {"a": {"0": 1.0, "1": 2.0}, "b": {"1": 2.0, "0": 1.0}}
        )
        is None
    )
    # non-numeric cells
    assert fast_codec.decode_dataframe({"a": {"0": "oops"}}) is None
    # scalar dict / empties / ragged rect
    assert fast_codec.decode_dataframe({"a": 1.0}) is None
    assert fast_codec.decode_dataframe({}) is None
    assert fast_codec.decode_dataframe([]) is None
    assert fast_codec.decode_dataframe([[1.0, 2.0], [3.0]]) is None


# --------------------------------------------------------------- e2e parity
@pytest.fixture(scope="module")
def app(model_collection_directory, trained_model_directories):
    from gordo_tpu.server import build_app
    from gordo_tpu.server import utils as server_utils

    server_utils.clear_model_caches()
    return build_app({"MODEL_COLLECTION_DIR": model_collection_directory})


@pytest.fixture(scope="module")
def client(app):
    return app.test_client()


_TIME_RE = re.compile(rb'"time-seconds": "[0-9.]+"')


def _normalized(resp) -> bytes:
    """Response bytes with the (run-varying) time-seconds value pinned."""
    return _TIME_RE.sub(b'"time-seconds": "T"', resp.data)


def _post_both_codecs(client, path, payload):
    """POST the same payload through the fast and pandas codecs; both must
    be 200 and byte-identical after pinning time-seconds."""
    body = json.dumps(payload).encode()
    fast = client.post(path, data=body, content_type="application/json")
    slow = client.post(
        path,
        data=body,
        content_type="application/json",
        headers={"X-Gordo-Codec": "pandas"},
    )
    assert fast.status_code == slow.status_code == 200
    assert _normalized(fast) == _normalized(slow)
    return fast


def test_e2e_rect_list_byte_identical(client, gordo_project, gordo_name):
    decode_before = metric_catalog.FAST_CODEC.value(op="decode")
    encode_before = metric_catalog.FAST_CODEC.value(op="encode")
    X = np.random.RandomState(0).rand(25, 4).tolist()
    _post_both_codecs(
        client,
        f"/gordo/v0/{gordo_project}/{gordo_name}/anomaly/prediction",
        {"X": X, "y": X},
    )
    # fast request decoded two frames (X and y) and encoded one response
    assert metric_catalog.FAST_CODEC.value(op="decode") == decode_before + 2
    assert metric_catalog.FAST_CODEC.value(op="encode") == encode_before + 1


def test_e2e_column_dict_byte_identical(
    client, gordo_project, gordo_name, X_payload
):
    payload = json.loads(
        json.dumps(
            {"X": dataframe_to_dict(X_payload), "y": dataframe_to_dict(X_payload)}
        )
    )
    _post_both_codecs(
        client,
        f"/gordo/v0/{gordo_project}/{gordo_name}/anomaly/prediction",
        payload,
    )


def test_e2e_base_prediction_byte_identical(
    client, gordo_project, gordo_name, X_payload
):
    _post_both_codecs(
        client,
        f"/gordo/v0/{gordo_project}/{gordo_name}/prediction",
        {"X": dataframe_to_dict(X_payload)},
    )


def test_e2e_all_columns_smoothed_nan_byte_identical(
    client, gordo_project, second_gordo_name, X_payload
):
    """machine-2 smooths over a 144 window → leading NaN rows in the
    smooth-* blocks: the nulls must round-trip identically."""
    payload = {
        "X": dataframe_to_dict(X_payload),
        "y": dataframe_to_dict(X_payload),
    }
    resp = _post_both_codecs(
        client,
        f"/gordo/v0/{gordo_project}/{second_gordo_name}/anomaly/prediction"
        "?all_columns=true",
        payload,
    )
    body = resp.get_json()
    smooth = [k for k in body["data"] if k.startswith("smooth-")]
    assert smooth, "expected smoothed columns with all_columns"
    assert b"null" in resp.data  # the rolling window's leading NaNs


def test_e2e_irregular_payload_falls_back_and_400s(
    client, gordo_project, gordo_name
):
    """A multi-level X takes the pandas fallback (counted) and then fails
    column verification exactly like before."""
    before = metric_catalog.FAST_CODEC_FALLBACK.value(op="decode")
    resp = client.post(
        f"/gordo/v0/{gordo_project}/{gordo_name}/prediction",
        json={"X": {"top": {"sub": {"0": 1.0, "1": 2.0}}}},
    )
    assert resp.status_code == 400
    assert metric_catalog.FAST_CODEC_FALLBACK.value(op="decode") == before + 1


def test_env_gate_disables_fast_path(
    client, gordo_project, gordo_name, monkeypatch
):
    """GORDO_TPU_FAST_CODEC=0 restores today's path: no fast counters move,
    and the header cannot re-enable it."""
    monkeypatch.setenv("GORDO_TPU_FAST_CODEC", "0")
    decode_before = metric_catalog.FAST_CODEC.value(op="decode")
    encode_before = metric_catalog.FAST_CODEC.value(op="encode")
    X = np.random.RandomState(0).rand(10, 4).tolist()
    resp = client.post(
        f"/gordo/v0/{gordo_project}/{gordo_name}/prediction",
        json={"X": X},
        headers={"X-Gordo-Codec": "fast"},
    )
    assert resp.status_code == 200
    assert metric_catalog.FAST_CODEC.value(op="decode") == decode_before
    assert metric_catalog.FAST_CODEC.value(op="encode") == encode_before


# ------------------------------------------------- json_response default
def test_json_serializer_default_known_types():
    import datetime

    assert json_serializer_default(datetime.date(2020, 1, 2)) == "2020-01-02"
    assert json_serializer_default(
        datetime.datetime(2020, 1, 2, 3, 4, 5)
    ) == "2020-01-02 03:04:05"
    assert json_serializer_default(np.float64(1.5)) == 1.5
    assert json_serializer_default(np.int64(7)) == 7


def test_json_serializer_default_raises_loudly():
    """default=str used to silently stringify ANY object into responses;
    unknown types must now raise."""

    class Opaque:
        pass

    with pytest.raises(TypeError, match="not JSON serializable"):
        json_serializer_default(Opaque())

    with pytest.raises(TypeError):
        simplejson.dumps(
            {"bad": Opaque()}, ignore_nan=True, default=json_serializer_default
        )


# ------------------------------------------------- full-native codec (ISSUE 19)
def _require_native():
    from gordo_tpu import native

    if not native.prebuild(block=True):
        pytest.skip("native library unavailable (no g++ in this image)")


def test_decode_body_coldict_native_parity():
    """The flat column-dict body parses natively into the exact frame the
    decode_dataframe dict branch yields — values, index, column order."""
    _require_native()
    idx = pd.date_range("2020-01-01", periods=8, freq="10min", tz="UTC")
    df = pd.DataFrame(
        np.random.RandomState(21).rand(8, 3), columns=["a", "b", "c"], index=idx
    )
    body = json.dumps({"X": dataframe_to_dict(df)}).encode()
    parsed = fast_codec.decode_body_xy(body)
    assert parsed is not None, "native coldict parse fell back"
    X, y = parsed
    assert y is None
    ref = fast_codec.decode_dataframe(json.loads(body)["X"])
    pd.testing.assert_frame_equal(X, ref)


def test_decode_body_coldict_null_cells_and_unsorted_keys():
    _require_native()
    body = (
        b'{"X": {"a": {"2": 3.0, "0": null, "1": 2.0},'
        b' "b": {"2": 30.0, "0": 10.0, "1": null}}}'
    )
    parsed = fast_codec.decode_body_xy(body)
    assert parsed is not None
    X, _ = parsed
    ref = fast_codec.decode_dataframe(json.loads(body)["X"])
    pd.testing.assert_frame_equal(X, ref)
    assert list(X.index) == [0, 1, 2]  # sorted like pandas


def test_decode_body_coldict_fallback_shapes():
    """Bodies the strict C grammar cannot prove equivalent to json.loads
    must fall back (None), never mis-parse."""
    _require_native()
    bails = [
        # ragged columns
        b'{"X": {"a": {"0": 1.0}, "b": {"0": 1.0, "1": 2.0}}}',
        # reordered keys across columns
        b'{"X": {"a": {"0": 1.0, "1": 2.0}, "b": {"1": 2.0, "0": 1.0}}}',
        # duplicate column name (json.loads collapses, last wins)
        b'{"X": {"a": {"0": 1.0}, "a": {"0": 2.0}}}',
        # duplicate index key within a column
        b'{"X": {"a": {"0": 1.0, "0": 2.0}}}',
        # escaped key spelling (same string, different bytes)
        b'{"X": {"\\u0061": {"0": 1.0}}}',
        # y as a column dict takes the Python path
        b'{"X": {"a": {"0": 1.0}}, "y": {"a": {"0": 1.0}}}',
        # non-numeric cell
        b'{"X": {"a": {"0": "oops"}}}',
        # multi-level payload
        b'{"X": {"top": {"sub": {"0": 1.0}}}}',
        # trailing garbage
        b'{"X": {"a": {"0": 1.0}}} x',
    ]
    for body in bails:
        assert fast_codec.decode_body_xy(body) is None, body


def test_encode_raw_keyed_template_runs_native(monkeypatch):
    """A DatetimeIndex response renders through the native template
    encoder (per-request template, C float formatting), byte-identical to
    the pandas path."""
    _require_native()
    from gordo_tpu import native

    monkeypatch.setattr(fast_codec, "_native_poisoned", False)
    calls = []
    real = native.encode_template

    def counting(*args):
        calls.append(1)
        return real(*args)

    monkeypatch.setattr(native, "encode_template", counting)
    idx = pd.date_range("2021-03-01", periods=9, freq="10min", tz="UTC")
    raw = _raw_frame(idx, with_nan=True)
    fragment = fast_codec.encode_raw(raw)
    assert calls, "keyed index bypassed the native template encoder"
    assert fragment == _slow_json(raw.to_pandas())


# ------------------------------------------- native degradation matrix (ISSUE 19)
def _golden_codec_bytes():
    """Reference bytes for one golden decode + one golden encode, computed
    through the pandas oracle (native-independent)."""
    idx = pd.date_range("2020-01-01", periods=6, freq="10min", tz="UTC")
    df = pd.DataFrame(
        np.random.RandomState(31).rand(6, 3), columns=["a", "b", "c"], index=idx
    )
    body = json.dumps({"X": dataframe_to_dict(df)}).encode()
    raw = _raw_frame(pd.RangeIndex(6), with_nan=True)
    return body, dataframe_from_dict(json.loads(body)["X"]), raw, _slow_json(
        raw.to_pandas()
    )


def _assert_degraded_parity():
    """With the native library unavailable (whatever the reason), both
    codec directions still produce byte/valu-identical results via the
    numpy/python lanes, and decode_body_xy falls back instead of erring."""
    body, ref_frame, raw, ref_fragment = _golden_codec_bytes()
    assert fast_codec.decode_body_xy(body) is None
    frame = fast_codec.decode_dataframe(json.loads(body)["X"])
    assert frame is not None
    np.testing.assert_array_equal(frame.to_numpy(), ref_frame.to_numpy())
    assert list(map(str, frame.index)) == list(map(str, ref_frame.index))
    fragment = fast_codec.encode_raw(raw)
    assert fragment == ref_fragment


def test_degradation_no_native_env(monkeypatch):
    """GORDO_TPU_NO_NATIVE=1: the kill switch byte-matches the fallback."""
    from gordo_tpu import native

    monkeypatch.setenv("GORDO_TPU_NO_NATIVE", "1")
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_encode_tpl_fn", None)
    monkeypatch.setattr(native, "_load_failed", False)
    monkeypatch.setattr(native, "_builder_thread", None)
    assert native.available() is False
    _assert_degraded_parity()


def test_degradation_missing_compiler(monkeypatch, tmp_path):
    """No g++ (the build subprocess cannot start): the failure latches and
    every codec path byte-matches the fallback."""
    from gordo_tpu import native

    def no_compiler(*args, **kwargs):
        raise OSError("g++ not found")

    monkeypatch.setenv("GORDO_TPU_NATIVE_CACHE", str(tmp_path))
    monkeypatch.delenv("GORDO_TPU_NO_NATIVE", raising=False)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_encode_tpl_fn", None)
    monkeypatch.setattr(native, "_load_failed", False)
    monkeypatch.setattr(native, "_builder_thread", None)
    monkeypatch.setattr(native, "_so_path_cache", None)
    monkeypatch.setattr(native.subprocess, "run", no_compiler)
    assert native.available() is False  # kicks the doomed background build
    thread = native._builder_thread
    if thread is not None:
        thread.join(timeout=10)
    assert native._load_failed is True
    assert native.available() is False
    _assert_degraded_parity()


def test_degradation_mid_build(monkeypatch, tmp_path):
    """available() while the compile is still in flight: False, no block,
    and the codec byte-matches the fallback until the artifact lands."""
    import threading

    from gordo_tpu import native

    release = threading.Event()

    def slow_build():
        release.wait(timeout=30)
        return None

    monkeypatch.setenv("GORDO_TPU_NATIVE_CACHE", str(tmp_path))
    monkeypatch.delenv("GORDO_TPU_NO_NATIVE", raising=False)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_encode_tpl_fn", None)
    monkeypatch.setattr(native, "_load_failed", False)
    monkeypatch.setattr(native, "_builder_thread", None)
    monkeypatch.setattr(native, "_so_path_cache", None)
    monkeypatch.setattr(native, "_build", slow_build)
    try:
        assert native.available() is False  # build now in flight, no block
        assert native._builder_thread is not None
        assert native._builder_thread.is_alive()
        _assert_degraded_parity()
    finally:
        release.set()
        native._builder_thread.join(timeout=10)
