"""
Unit tests for the fault-domain layer (util/faults.py): classification,
retry/backoff, the fault-plan parser, and the validation helpers.
"""

import json

import numpy as np
import pandas as pd
import pytest

from gordo_tpu.util import faults
from gordo_tpu.util.faults import (
    FaultPlan,
    FaultPolicy,
    InjectedOOM,
    NonFiniteDataError,
    PermanentFault,
    QuarantineRecord,
    TransientFault,
    is_oom,
    is_transient,
    retry_call,
)


# ---------------------------------------------------------- classification
def test_classification():
    assert is_transient(TransientFault("x"))
    assert is_transient(TimeoutError("x"))
    assert is_transient(ConnectionError("x"))
    assert is_transient(OSError("x"))
    assert not is_transient(PermanentFault("x"))
    assert not is_transient(ValueError("x"))
    assert not is_transient(NonFiniteDataError("x"))


def test_classification_by_type_name():
    """requests/urllib3 exception types are recognized without importing
    those libraries here (matched by type name in the MRO)."""

    class ReadTimeout(Exception):
        pass

    assert is_transient(ReadTimeout("x"))


def test_is_oom():
    assert is_oom(InjectedOOM("RESOURCE_EXHAUSTED: injected"))
    assert is_oom(MemoryError())
    assert is_oom(RuntimeError("RESOURCE_EXHAUSTED: out of memory on device"))
    assert is_oom(RuntimeError("Allocator ran OOM trying to allocate 2GiB"))
    assert not is_oom(RuntimeError("shape mismatch"))
    assert not is_oom(TransientFault("x"))


# ----------------------------------------------------------------- policy
def test_policy_backoff_is_exponential_and_capped():
    p = FaultPolicy(backoff_base=1.0, backoff_factor=2.0, backoff_max=3.0, jitter=0.0)
    assert p.backoff(1) == 1.0
    assert p.backoff(2) == 2.0
    assert p.backoff(3) == 3.0  # capped
    assert p.backoff(10) == 3.0


def test_policy_backoff_jitter_is_deterministic():
    p = FaultPolicy(backoff_base=1.0, jitter=0.5)
    assert p.backoff(1, "machine-a") == p.backoff(1, "machine-a")
    # different machines get different (decorrelated) jitter
    assert p.backoff(1, "machine-a") != p.backoff(1, "machine-b")
    # jitter only ever lengthens the delay, bounded by the fraction
    assert 1.0 <= p.backoff(1, "machine-a") <= 1.5


def test_policy_from_env(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_FAULT_MAX_ATTEMPTS", "5")
    monkeypatch.setenv("GORDO_TPU_FAULT_BACKOFF_BASE", "0.25")
    p = FaultPolicy.from_env()
    assert p.max_attempts == 5
    assert p.backoff_base == 0.25
    # invalid values fall back to defaults instead of crashing the build
    monkeypatch.setenv("GORDO_TPU_FAULT_MAX_ATTEMPTS", "banana")
    assert FaultPolicy.from_env().max_attempts == FaultPolicy.max_attempts


def test_retry_call_retries_transient_then_succeeds():
    policy = FaultPolicy(max_attempts=3, backoff_base=0.0, jitter=0.0)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientFault("flake")
        return "ok"

    result, attempts = retry_call(flaky, policy, sleep=lambda _s: None)
    assert result == "ok" and attempts == 3


def test_retry_call_raises_permanent_immediately():
    policy = FaultPolicy(max_attempts=5, backoff_base=0.0)
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise PermanentFault("dead")

    with pytest.raises(PermanentFault):
        retry_call(broken, policy, sleep=lambda _s: None)
    assert calls["n"] == 1


def test_retry_call_exhausts_budget():
    policy = FaultPolicy(max_attempts=3, backoff_base=0.0)
    calls = {"n": 0}

    def always_flaky():
        calls["n"] += 1
        raise TransientFault("flake")

    with pytest.raises(TransientFault):
        retry_call(always_flaky, policy, sleep=lambda _s: None)
    assert calls["n"] == 3


# ------------------------------------------------------------- fault plan
def test_plan_parse_and_fire_counts():
    plan = FaultPlan.parse(
        json.dumps(
            {
                "rules": [
                    {"site": "data_fetch", "machine": "m-1", "times": 2,
                     "error": "transient"},
                    {"site": "data_fetch", "machine": "m-2", "times": -1,
                     "error": "permanent"},
                ]
            }
        )
    )
    # m-1: exactly two firings, then clean
    with pytest.raises(TransientFault):
        plan.fire("data_fetch", machine="m-1")
    with pytest.raises(TransientFault):
        plan.fire("data_fetch", machine="m-1")
    plan.fire("data_fetch", machine="m-1")  # exhausted: no raise
    # m-2: every invocation, forever
    for _ in range(3):
        with pytest.raises(PermanentFault):
            plan.fire("data_fetch", machine="m-2")
    # unmatched machine/site: never fires
    plan.fire("data_fetch", machine="m-3")
    plan.fire("bucket_compile", machines=["m-1", "m-2"])


def test_plan_bucket_compile_matches_membership():
    plan = FaultPlan.parse(
        '[{"site": "bucket_compile", "machine": "m-4", '
        '"times": 1, "error": "resource_exhausted"}]'
    )
    plan.fire("bucket_compile", machines=["m-1", "m-2"])  # not a member
    with pytest.raises(InjectedOOM) as exc_info:
        plan.fire("bucket_compile", machines=["m-3", "m-4"])
    assert is_oom(exc_info.value)
    plan.fire("bucket_compile", machines=["m-3", "m-4"])  # budget spent


def test_plan_from_file(tmp_path):
    plan_file = tmp_path / "plan.json"
    plan_file.write_text(
        '{"rules": [{"site": "data_fetch", "machine": "m", '
        '"error": "permanent"}]}'
    )
    plan = FaultPlan.parse(f"@{plan_file}")
    with pytest.raises(PermanentFault):
        plan.fire("data_fetch", machine="m")


def test_plan_env_roundtrip(monkeypatch):
    monkeypatch.setenv(
        faults.PLAN_ENV,
        '[{"site": "data_fetch", "machine": "m", "error": "transient"}]',
    )
    faults.reset_plan()
    with pytest.raises(TransientFault):
        faults.fault_point("data_fetch", machine="m")
    faults.fault_point("data_fetch", machine="m")  # budget spent
    # counters survive repeated get_plan() calls while env is unchanged
    faults.fault_point("data_fetch", machine="m")
    monkeypatch.delenv(faults.PLAN_ENV)
    faults.fault_point("data_fetch", machine="m")  # no plan: no-op


def test_maybe_poison_ndarray_and_dataframe(monkeypatch):
    monkeypatch.setenv(
        faults.PLAN_ENV, '[{"site": "poison_nan", "machine": "m"}]'
    )
    faults.reset_plan()
    X = np.ones((4, 3), dtype=np.float32)
    Xp = faults.maybe_poison("m", X)
    assert np.isnan(Xp[:, 0]).all()
    assert np.isfinite(X).all()  # original untouched
    df = pd.DataFrame(np.ones((4, 3)))
    dfp = faults.maybe_poison("m", df)
    assert dfp.iloc[:, 0].isna().all()
    assert np.isfinite(df.to_numpy()).all()
    # non-matching machine passes through unchanged (identity)
    assert faults.maybe_poison("other", X) is X


# ------------------------------------------------------------- validation
def test_non_finite_report():
    assert faults.non_finite_report(np.ones((3, 2))) is None
    X = np.ones((3, 2))
    X[1, 1] = np.nan
    report = faults.non_finite_report(X)
    assert "1 non-finite" in report and "X" in report
    y = np.full((3, 1), np.inf)
    assert "y" in faults.non_finite_report(np.ones((3, 2)), y)
    # integer arrays are trivially finite
    assert faults.non_finite_report(np.ones((3, 2), dtype=np.int64)) is None


def test_params_non_finite():
    good = {"w": np.ones((2, 2)), "b": np.zeros(2)}
    assert faults.params_non_finite(good, np.array([0.1, 0.05])) is None
    assert "loss" in faults.params_non_finite(good, np.array([0.1, np.nan]))
    bad = {"w": np.array([[1.0, np.inf]])}
    assert "parameters" in faults.params_non_finite(bad)


def test_quarantine_record_to_dict():
    record = QuarantineRecord(
        machine="m", stage="data_fetch", reason="permanent_fetch_failure",
        error="boom", attempts=3,
    )
    d = record.to_dict()
    assert d["quarantined"] is True
    assert d["machine"] == "m" and d["attempts"] == 3


def test_quarantine_record_attributes_observing_host(monkeypatch):
    """A merged pod-scale quarantine report must say WHICH host observed
    each fault: host/process_index ride along in every record."""
    monkeypatch.setenv("GORDO_TPU_HOST_ID", "host-east-3")
    monkeypatch.setenv("GORDO_TPU_PROCESS_ID", "3")
    d = QuarantineRecord(
        machine="m", stage="data_fetch", reason="r", error="e"
    ).to_dict()
    assert d["host"] == "host-east-3"
    assert d["process_index"] == 3


def test_quarantine_record_attribution_defaults(monkeypatch):
    """Without the env knobs the attribution still resolves: hostname-pid
    and the live jax process index (0 in a single-process world)."""
    monkeypatch.delenv("GORDO_TPU_HOST_ID", raising=False)
    monkeypatch.delenv("GORDO_TPU_PROCESS_ID", raising=False)
    d = QuarantineRecord(machine="m", stage="s", reason="r", error="e").to_dict()
    assert d["host"] and "-" in d["host"]
    assert isinstance(d["process_index"], int) and d["process_index"] == 0
