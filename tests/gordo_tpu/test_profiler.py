"""
Sampling profiler (ISSUE 17, layer 1): disabled-path guarantees, burst
capture, export formats, the gated debug endpoints, and the live
profile-smoke subprocess (`make profile-smoke` wired into tier-1).
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from gordo_tpu.observability import profiler

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@pytest.fixture(autouse=True)
def _clean_profiler(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PROFILE_HZ", raising=False)
    monkeypatch.delenv("GORDO_TPU_PROFILE_MAX_STACKS", raising=False)
    monkeypatch.delenv("GORDO_TPU_DEBUG_ENDPOINTS", raising=False)
    profiler.reset()
    yield
    profiler.reset()


# ----------------------------------------------------------- disabled path
def test_disabled_registration_is_shared_noop_singleton():
    """With no profiler/debug knob set, register_thread must return THE
    shared no-op handle — same object every call, zero state touched."""
    reg_a = profiler.register_thread("lane-a")
    reg_b = profiler.register_thread("lane-b")
    assert reg_a is profiler.NOOP_REGISTRATION
    assert reg_b is profiler.NOOP_REGISTRATION
    assert profiler.registered_threads() == {}
    assert not profiler.steady_running()
    reg_a.unregister()  # harmless no-op


def test_registration_armed_by_debug_endpoints_alone(monkeypatch):
    """Burst capture through /debug/profile needs thread names even with
    steady sampling off, so GORDO_TPU_DEBUG_ENDPOINTS arms registration —
    but must NOT start the steady sampler."""
    monkeypatch.setenv("GORDO_TPU_DEBUG_ENDPOINTS", "1")
    reg = profiler.register_thread("debug-armed")
    assert reg is not profiler.NOOP_REGISTRATION
    assert "debug-armed" in profiler.registered_threads().values()
    assert not profiler.steady_running()
    reg.unregister()
    assert "debug-armed" not in profiler.registered_threads().values()


def test_batcher_submit_adds_zero_observability_allocations(monkeypatch):
    """Disabled-path micro-benchmark: with every ISSUE 17 knob unset, a
    steady-state batcher submit must allocate NOTHING attributable to the
    new observability modules (profiler/attribution/sentinel) — the
    serving path is byte-identical to a build without them."""
    import tracemalloc

    from gordo_tpu.models.models import AutoEncoder
    from gordo_tpu.observability import attribution, sentinel
    from gordo_tpu.server.batcher import CrossModelBatcher

    monkeypatch.delenv("GORDO_TPU_PERF_ATTRIBUTION", raising=False)
    monkeypatch.delenv("GORDO_TPU_PERF_SENTINEL", raising=False)

    rng = np.random.RandomState(0)
    X = rng.rand(64, 4)
    est = AutoEncoder(kind="feedforward_hourglass", epochs=1)
    est.fit(X, X)
    b = CrossModelBatcher(window_ms=0, max_batch=8)
    X32 = X.astype(np.float32)
    # warm: compile the fused program, allocate stacking buffers, start
    # the dispatcher loop (whose one register_thread call is the no-op)
    b.submit(est.spec_, est.params_, X32)

    module_files = (
        profiler.__file__, attribution.__file__, sentinel.__file__,
    )
    filters = [tracemalloc.Filter(True, path) for path in module_files]
    tracemalloc.start(5)
    try:
        for _ in range(5):
            b.submit(est.spec_, est.params_, X32)
            attribution.observe("m", 0.01, {"decode": 0.001})  # gated off
        snapshot = tracemalloc.take_snapshot().filter_traces(filters)
    finally:
        tracemalloc.stop()
    stats = snapshot.statistics("lineno")
    assert stats == [], [
        (str(stat.traceback), stat.size) for stat in stats
    ]


# --------------------------------------------------------------- sampling
def test_steady_sampler_samples_registered_thread(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PROFILE_HZ", "250")
    stop = threading.Event()

    def spin():
        profiler.register_thread("hot-spinner")
        while not stop.is_set():
            sum(range(500))

    worker = threading.Thread(target=spin, daemon=True)
    worker.start()
    try:
        deadline = time.monotonic() + 5.0
        while (
            profiler.steady_counter().total == 0
            and time.monotonic() < deadline
        ):
            time.sleep(0.02)
    finally:
        stop.set()
        worker.join(timeout=2)
    snap = profiler.snapshot()
    assert snap["running"]
    assert snap["hz"] == 250
    assert snap["total_samples"] > 0
    assert any(
        line.startswith("hot-spinner;") for line in snap["collapsed"]
    )
    assert profiler.top_stacks(5)


def test_burst_captures_the_calling_registered_thread(monkeypatch):
    """A burst requested FROM a registered thread (the event-loop lane
    serving /debug/profile) must still capture that thread's own stack —
    the sampling loop runs on a helper thread while the caller blocks."""
    monkeypatch.setenv("GORDO_TPU_DEBUG_ENDPOINTS", "1")
    reg = profiler.register_thread("burst-caller")
    try:
        counter = profiler.burst(0.2, hz=300)
    finally:
        reg.unregister()
    report = counter.to_dict()
    assert report["total_samples"] > 0
    assert any(
        line.startswith("burst-caller;") for line in report["collapsed"]
    )
    # the caller's own frames are in the capture
    assert any("test_profiler" in line for line in report["collapsed"])
    # burst is independent of the steady sampler
    assert not profiler.steady_running()


# ------------------------------------------------------------ stack counter
def test_stack_counter_overflow_stays_bounded():
    counter = profiler.StackCounter(limit=16)
    frame = sys._getframe()
    for i in range(40):
        counter.fold(f"thread-{i}", frame)
    report = counter.to_dict()
    assert report["total_samples"] == 40
    # 16 distinct keys + the single overflow bucket
    assert report["distinct_stacks"] == 17
    assert report["overflow_samples"] == 24


def test_collapsed_and_chrome_trace_formats():
    counter = profiler.StackCounter(limit=64)
    frame = sys._getframe()
    for _ in range(3):
        counter.fold("lane", frame)
    lines = counter.collapsed()
    assert len(lines) == 1
    stack, count = lines[0].rsplit(" ", 1)
    assert int(count) == 3
    assert stack.startswith("lane;")
    assert "test_profiler.py:" in stack

    trace = counter.chrome_trace(hz=100.0)
    (event,) = trace["traceEvents"]
    assert event["ph"] == "X"
    assert event["tid"] == "lane"
    assert event["dur"] == pytest.approx(3 / 100.0 * 1e6)
    assert event["args"]["samples"] == 3
    assert trace["otherData"]["totalSamples"] == 3


# --------------------------------------------------------- debug endpoints
def test_profile_and_perf_endpoints_gated_then_live(tmp_path, monkeypatch):
    from gordo_tpu.server import utils as server_utils
    from gordo_tpu.server.server import build_app

    server_utils.clear_model_caches()
    app = build_app({"MODEL_COLLECTION_DIR": str(tmp_path)})
    client = app.test_client()
    # gated off: 404, indistinguishable from an unknown route
    for path in ("/debug/profile", "/debug/perf"):
        assert client.get(path).status_code == 404, path

    monkeypatch.setenv("GORDO_TPU_DEBUG_ENDPOINTS", "1")
    resp = client.get("/debug/profile?seconds=0.05&hz=50")
    assert resp.status_code == 200
    body = resp.get_json()
    assert "total_samples" in body
    assert "steady" in body

    resp = client.get("/debug/profile?seconds=0.05&hz=50&format=collapsed")
    assert resp.status_code == 200
    assert resp.mimetype == "text/plain"

    resp = client.get("/debug/profile?seconds=0.05&hz=50&format=chrome")
    assert "traceEvents" in resp.get_json()

    body = client.get("/debug/perf").get_json()
    assert "attribution" in body
    assert "sentinel" in body


# ------------------------------------------------------------ profile-smoke
def test_profile_smoke_subprocess():
    """`make profile-smoke` in miniature: the script boots a live
    event-loop server, bursts /debug/profile, and must find the
    event-loop frames in its own capture."""
    env = dict(os.environ)
    env["GORDO_TPU_PROFILE_SMOKE_SECONDS"] = "0.3"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "profile_smoke.py"),
        ],
        capture_output=True, text=True, timeout=180, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "profile-smoke: OK" in proc.stdout
