"""Grafana dashboard generation (reference resources/grafana/dashboards)."""

import json
import re

from gordo_tpu.observability import (
    build_dashboard,
    chaos_dashboard,
    drift_dashboard,
    fleet_dashboard,
    gateway_dashboard,
    machines_dashboard,
    perf_dashboard,
    resilience_dashboard,
    servers_dashboard,
    telemetry,
    write_dashboards,
)
from gordo_tpu.observability import metrics as metric_catalog  # noqa: F401
from gordo_tpu.server.prometheus import metrics as server_metrics

_ALL_DASHBOARDS = (
    servers_dashboard,
    machines_dashboard,
    build_dashboard,
    resilience_dashboard,
    fleet_dashboard,
    gateway_dashboard,
    perf_dashboard,
)


def _all_exprs(dash):
    for panel in dash["panels"]:
        for target in panel["targets"]:
            yield target["expr"]


def test_dashboards_reference_live_metric_names():
    """Every metric a dashboard queries must be one the system exports —
    either a prometheus_client metric the server registers
    (server/prometheus/metrics.py) or a telemetry-registry series from the
    catalog (observability/metrics.py) — so dashboards can't silently
    drift from the metrics modules."""
    exported = {
        "gordo_server_request_duration_seconds",
        "gordo_server_requests_total",
        "gordo_server_info",
        "gordo_server_batcher_items",
        "gordo_server_batcher_device_calls",
        "gordo_server_batcher_largest_batch",
        "gordo_server_batcher_specs",
    }
    # the exported set itself must match what metrics.py registers
    src = open(server_metrics.__file__).read()
    for name in exported:
        assert f'"{name}"' in src, name
    # plus every series registered through the telemetry catalog (importing
    # it above registered them in the default registry)
    exported |= set(telemetry.default_registry().names())

    suffix = r"(?:_bucket|_count|_sum)?"
    metric_re = re.compile(
        r"(gordo_(?:server|build|gateway)_[a-z0-9_]+?)" + suffix + r"[{\[\s)]"
    )
    for dashboard in _ALL_DASHBOARDS:
        for expr in _all_exprs(dashboard()):
            names = metric_re.findall(expr)
            assert names, expr
            for name in names:
                base = re.sub(r"_(bucket|count|sum)$", "", name)
                assert base in exported, (base, expr)


def test_dashboard_structure():
    for dashboard in _ALL_DASHBOARDS:
        dash = dashboard()
        ids = [p["id"] for p in dash["panels"]]
        assert len(ids) == len(set(ids))
        assert dash["uid"]
        var_names = [v["name"] for v in dash["templating"]["list"]]
        assert "project" in var_names
        for panel in dash["panels"]:
            assert panel["type"] in ("timeseries", "stat")
            # single y-scale: no overrides introducing a second axis
            assert panel["fieldConfig"]["overrides"] == []


def test_latency_panels_use_quantiles_not_averages():
    for dashboard in (servers_dashboard, build_dashboard):
        dash = dashboard()
        latency_exprs = [
            e for e in _all_exprs(dash) if "_seconds_bucket" in e
        ]
        assert latency_exprs
        for expr in latency_exprs:
            assert "histogram_quantile" in expr


def test_write_dashboards_roundtrip(tmp_path):
    paths = write_dashboards(str(tmp_path))
    assert len(paths) == 9
    for path in paths:
        with open(path) as fh:
            dash = json.load(fh)
        assert dash["panels"]


def test_checked_in_dashboards_are_current():
    """resources/grafana/dashboards must match the generator output."""
    import os

    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    out_dir = os.path.join(repo_root, "resources", "grafana", "dashboards")
    for name, build in (
        ("gordo_tpu_servers.json", servers_dashboard),
        ("gordo_tpu_machines.json", machines_dashboard),
        ("gordo_tpu_build.json", build_dashboard),
        ("gordo_tpu_resilience.json", resilience_dashboard),
        ("gordo_tpu_fleet.json", fleet_dashboard),
        ("gordo_tpu_gateway.json", gateway_dashboard),
        ("gordo_tpu_drift.json", drift_dashboard),
        ("gordo_tpu_chaos.json", chaos_dashboard),
        ("gordo_tpu_perf.json", perf_dashboard),
    ):
        with open(os.path.join(out_dir, name)) as fh:
            assert json.load(fh) == build(), f"{name} is stale — regenerate with " \
                "python -m gordo_tpu.observability.grafana"
