"""
Cross-model serving batcher: correctness against the direct path, grouping,
and end-to-end through the WSGI app under concurrent load.
"""

import json
import threading
import time

import numpy as np
import pytest

from gordo_tpu.models.models import AutoEncoder
from gordo_tpu.server import batcher as batcher_mod
from gordo_tpu.server.batcher import CrossModelBatcher


def _fitted_autoencoder(seed: int, n_features: int = 4) -> AutoEncoder:
    rng = np.random.RandomState(seed)
    est = AutoEncoder(kind="feedforward_hourglass", epochs=1)
    X = rng.rand(64, n_features)
    est.fit(X, X)
    return est


@pytest.fixture(scope="module")
def models():
    return [_fitted_autoencoder(seed) for seed in range(3)]


def test_batched_matches_direct(models):
    b = CrossModelBatcher(window_ms=10, max_batch=8)
    rng = np.random.RandomState(0)
    X = rng.rand(50, 4).astype(np.float32)

    direct = [m.predict(X) for m in models]

    results = [None] * len(models)

    def run(i):
        results[i] = b.submit(models[i].spec_, models[i].params_, X)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(len(models))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    for got, want in zip(results, direct):
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    assert b.stats["items"] == len(models)
    # at least two predicts fused into one device call
    assert b.stats["device_calls"] < len(models)
    assert b.stats["largest_batch"] >= 2


def test_mixed_shapes_grouped_separately(models):
    b = CrossModelBatcher(window_ms=10, max_batch=8)
    rng = np.random.RandomState(1)
    X_small = rng.rand(20, 4).astype(np.float32)
    X_large = rng.rand(200, 4).astype(np.float32)

    outputs = {}

    def run(key, m, X):
        outputs[key] = b.submit(m.spec_, m.params_, X)

    threads = [
        threading.Thread(target=run, args=("s0", models[0], X_small)),
        threading.Thread(target=run, args=("l1", models[1], X_large)),
        threading.Thread(target=run, args=("s2", models[2], X_small)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    np.testing.assert_allclose(
        outputs["s0"], models[0].predict(X_small), rtol=1e-6, atol=1e-7
    )
    np.testing.assert_allclose(
        outputs["l1"], models[1].predict(X_large), rtol=1e-6, atol=1e-7
    )
    assert outputs["s0"].shape == (20, 4)
    assert outputs["l1"].shape == (200, 4)


def test_stack_buffers_reused_across_device_calls(models):
    """The per-fuse-width stacking buffers are allocated once and reused:
    steady-state serving must not re-allocate a (batch, …) array + index
    vector on every fused call."""
    b = CrossModelBatcher(window_ms=0, max_batch=8)
    rng = np.random.RandomState(2)
    X = rng.rand(30, 4).astype(np.float32)

    first = b.submit(models[0].spec_, models[0].params_, X)
    assert len(b._stack_buffers) == 1
    buffers_after_first = {k: (id(v[0]), id(v[1])) for k, v in b._stack_buffers.items()}

    second = b.submit(models[0].spec_, models[0].params_, X)
    assert {
        k: (id(v[0]), id(v[1])) for k, v in b._stack_buffers.items()
    } == buffers_after_first  # same arrays, not reallocations
    np.testing.assert_allclose(first, models[0].predict(X), rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(second, first, rtol=0, atol=0)

    # a different padded shape gets its own buffer; the cache stays bounded
    X_large = rng.rand(300, 4).astype(np.float32)
    b.submit(models[0].spec_, models[0].params_, X_large)
    assert len(b._stack_buffers) == 2


def test_error_fans_out_to_waiters(models):
    b = CrossModelBatcher(window_ms=5, max_batch=8)
    bad_params = "not-a-pytree-of-arrays"
    with pytest.raises(Exception):
        b.submit(models[0].spec_, bad_params, np.random.rand(10, 4))


def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_SERVING_BATCH", raising=False)
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    assert batcher_mod.get_batcher() is None
    assert batcher_mod.maybe_submit(None, None, None) is None


def _assert_payload_close(got, want, path=""):
    """Structural equality with approximate float leaves (rtol as in
    test_batched_matches_direct)."""
    assert type(got) is type(want), f"{path}: {type(got)} != {type(want)}"
    if isinstance(got, dict):
        assert got.keys() == want.keys(), f"{path}: keys differ"
        for k in got:
            _assert_payload_close(got[k], want[k], f"{path}.{k}")
    elif isinstance(got, list):
        assert len(got) == len(want), f"{path}: lengths differ"
        for i, (g, w) in enumerate(zip(got, want)):
            _assert_payload_close(g, w, f"{path}[{i}]")
    elif isinstance(got, float):
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7, err_msg=path)
    else:
        assert got == want, f"{path}: {got!r} != {want!r}"


def test_server_end_to_end_with_batching(
    monkeypatch,
    model_collection_directory,
    trained_model_directories,
    gordo_project,
    gordo_name,
):
    """Concurrent anomaly POSTs through the WSGI app with batching enabled
    produce the same payloads as with batching disabled."""
    from gordo_tpu.server.server import build_app

    app = build_app({"MODEL_COLLECTION_DIR": model_collection_directory})
    client = app.test_client()
    rng = np.random.RandomState(0)
    X = rng.rand(40, 4).tolist()
    body = json.dumps({"X": X, "y": X}).encode()
    path = f"/gordo/v0/{gordo_project}/{gordo_name}/anomaly/prediction"

    def post():
        return client.post(path, data=body, content_type="application/json")

    monkeypatch.setattr(batcher_mod, "_batcher", None)
    monkeypatch.delenv("GORDO_TPU_SERVING_BATCH", raising=False)
    baseline = post()
    assert baseline.status_code == 200

    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    responses = [None] * 4
    threads = [
        threading.Thread(
            target=lambda i=i: responses.__setitem__(i, post())
        )
        for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for resp in responses:
        assert resp.status_code == 200
        # 'time-seconds' is wall time; the payload proper must match the
        # direct path. Float comparison is approximate: the stacked program
        # batches however many requests coalesce in the window, and XLA does
        # not guarantee bitwise-identical float32 results across vmap widths
        # (same tolerance as test_batched_matches_direct).
        _assert_payload_close(
            json.loads(resp.data)["data"], json.loads(baseline.data)["data"]
        )
    monkeypatch.setattr(batcher_mod, "_batcher", None)


# ------------------------------------------------------------ auto (self-A/B)
def test_auto_mode_calibrates_once_and_honours_decision(models, monkeypatch):
    """auto mode: one measured A/B per spec; a losing spec predicts direct
    (submit returns None), a winning spec keeps batching."""
    monkeypatch.setenv("GORDO_TPU_BATCH_AB_USERS", "2")
    monkeypatch.setenv("GORDO_TPU_BATCH_AB_ROUNDS", "1")
    b = CrossModelBatcher(max_batch=8, self_ab=True)
    m = models[0]
    X = np.random.RandomState(3).rand(30, 4).astype(np.float32)

    out = b.submit(m.spec_, m.params_, X)
    assert m.spec_ in b._spec_on  # calibration ran and recorded a decision
    decision = b._spec_on[m.spec_]
    if decision:
        assert out is not None
        np.testing.assert_allclose(out, m.predict(X), rtol=1e-5, atol=1e-6)
    else:
        assert out is None  # stood down: caller goes direct

    # second submit must not re-calibrate (decision is sticky)
    calls = []
    monkeypatch.setattr(
        b, "_calibrate", lambda *a, **k: calls.append(1) or True
    )
    b.submit(m.spec_, m.params_, X)
    assert not calls


def test_auto_mode_forced_decision_routes(models):
    """With the decision pinned, submit() either batches or hands back."""
    m = models[0]
    X = np.random.RandomState(4).rand(16, 4).astype(np.float32)
    b = CrossModelBatcher(max_batch=8, self_ab=True)
    b._spec_on[m.spec_] = False
    assert b.submit(m.spec_, m.params_, X) is None
    b._spec_on[m.spec_] = True
    out = b.submit(m.spec_, m.params_, X)
    np.testing.assert_allclose(out, m.predict(X), rtol=1e-5, atol=1e-6)


def test_env_auto_enables_self_ab(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "auto")
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    b = batcher_mod.get_batcher()
    assert b is not None and b.self_ab
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    b = batcher_mod.get_batcher()
    assert b is not None and not b.self_ab
    monkeypatch.setattr(batcher_mod, "_batcher", None)


def test_auto_mode_losing_measurement_stands_down(models, monkeypatch):
    """A losing self-A/B stands the spec down: the recorded decision is
    False, the triggering submit hands back to the direct path, and
    subsequent predicts bypass the batch queue entirely (no new device
    calls through the batcher)."""
    import time

    import numpy as np

    monkeypatch.setenv("GORDO_TPU_BATCH_AB_USERS", "2")
    monkeypatch.setenv("GORDO_TPU_BATCH_AB_ROUNDS", "2")
    monkeypatch.setenv("GORDO_TPU_BATCH_AB_HOSTWORK_MS", "0")
    b = CrossModelBatcher(max_batch=8, self_ab=True)
    m = models[0]
    X = np.random.RandomState(5).rand(20, 4).astype(np.float32)

    # rig the batched arm to lose the A/B deterministically
    real_force = b._force_submit

    def slow_submit(spec, params, X):
        time.sleep(0.02)
        return real_force(spec, params, X)

    monkeypatch.setattr(b, "_force_submit", slow_submit)
    out = b.submit(m.spec_, m.params_, X)
    assert b._spec_on[m.spec_] is False  # measured loss recorded
    assert out is None  # the triggering submit already goes direct
    monkeypatch.setattr(b, "_force_submit", real_force)

    # subsequent predicts take the direct path: submit hands back and the
    # batcher's device-call counter stays frozen
    calls_before = b.stats["device_calls"]
    for _ in range(3):
        assert b.submit(m.spec_, m.params_, X) is None
    assert b.stats["device_calls"] == calls_before

    # ...including through the real predict route (maybe_submit -> None ->
    # the estimator's direct program), which must still produce output
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "auto")
    monkeypatch.setattr(batcher_mod, "_batcher", b)
    direct_out = m.predict(X)
    assert direct_out.shape == (20, 4)
    assert b.stats["device_calls"] == calls_before
    monkeypatch.setattr(batcher_mod, "_batcher", None)


def test_calibration_interrupt_does_not_leak(models, monkeypatch):
    """A BaseException (worker shutdown) mid-self-A/B must propagate AND
    leave the calibrating set — a leaked entry would silently pin the spec
    to the direct path forever with no recorded decision."""
    import gordo_tpu.ops.train as train_mod

    def boom(spec):
        raise SystemExit(1)

    monkeypatch.setattr(train_mod, "predict_fn", boom)
    b = CrossModelBatcher(self_ab=True)
    m = models[0]
    X = np.random.RandomState(0).rand(10, 4).astype(np.float32)
    with pytest.raises(SystemExit):
        b.submit(m.spec_, m.params_, X)
    assert m.spec_ not in b._calibrating
    # no decision recorded: the next submit re-attempts calibration
    assert m.spec_ not in b._spec_on


# --------------------------------------------- resilience (PR 3): timeouts,
# abandoned items, fused-group fault isolation
def _set_plan(monkeypatch, rules):
    from gordo_tpu.util import faults

    monkeypatch.setenv(faults.PLAN_ENV, json.dumps({"rules": rules}))
    faults.reset_plan()


@pytest.fixture()
def _fresh_plan(monkeypatch):
    from gordo_tpu.util import faults

    monkeypatch.delenv(faults.PLAN_ENV, raising=False)
    faults.reset_plan()
    yield
    faults.reset_plan()


def test_timeout_abandons_item_and_skips_it_at_fanout(
    models, monkeypatch, _fresh_plan, caplog
):
    """A wedged device call: the waiter times out (counted, logged once),
    and an item still queued behind the wedge is SKIPPED at fan-out rather
    than computed for nobody."""
    import logging

    from gordo_tpu.observability import metrics as metric_catalog

    _set_plan(
        monkeypatch,
        [{"site": "serve_device_call", "times": 1, "error": "wedge",
          "seconds": 1.0}],
    )
    b = CrossModelBatcher(window_ms=0, max_batch=8, timeout_s=0.2)
    X = np.random.RandomState(7).rand(12, 4).astype(np.float32)
    abandoned_before = metric_catalog.BATCHER_ABANDONED.value()

    errors = {}

    def submit(key, i):
        try:
            b.submit(models[i].spec_, models[i].params_, X)
        except BaseException as exc:  # noqa: BLE001
            errors[key] = exc

    with caplog.at_level(logging.WARNING, logger="gordo_tpu.server.batcher"):
        t1 = threading.Thread(target=submit, args=("wedged", 0))
        t1.start()
        time.sleep(0.4)  # the dispatcher is now inside the wedged call
        # the watchdog sees the dispatcher stuck in ONE device call
        assert b.device_call_stuck_s() > 0.2
        t2 = threading.Thread(target=submit, args=("queued", 1))
        t2.start()
        t1.join()
        t2.join()
    assert isinstance(errors["wedged"], TimeoutError)
    assert isinstance(errors["queued"], TimeoutError)
    assert metric_catalog.BATCHER_ABANDONED.value() == abandoned_before + 2
    # the wedged item was already inside its device call (computed anyway);
    # the queued one was dequeued AFTER its waiter left and skipped
    deadline = time.monotonic() + 5
    while b.stats["device_calls"] < 1 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert b.stats["items"] == 1
    # the spec/shape is logged once, not once per abandon
    abandon_logs = [
        r for r in caplog.records if "abandoned by its waiter" in r.message
    ]
    assert len(abandon_logs) == 1
    # the batcher recovers: a fresh submit (no rule left) serves normally
    out = b.submit(models[2].spec_, models[2].params_, X)
    np.testing.assert_allclose(
        out, models[2].predict(X), rtol=1e-6, atol=1e-7
    )
    assert b.device_call_stuck_s() == 0.0


def test_ring_submit_eight_concurrent_producers(models):
    """ISSUE 11: the wait-free submit ring under 8 concurrent producers,
    several rounds each — every result matches the direct path (nothing
    lost, nothing cross-wired between waiters), and the mid-run idle gap
    forces the dispatcher through its park/eventfd-wake path."""
    b = CrossModelBatcher(window_ms=0, max_batch=64)
    rng = np.random.RandomState(11)
    X = rng.rand(25, 4).astype(np.float32)
    direct = [m.predict(X) for m in models]
    rounds = 6
    results = [[None] * rounds for _ in range(8)]

    def producer(t):
        for r in range(rounds):
            results[t][r] = b.submit(
                models[t % 3].spec_, models[t % 3].params_, X
            )
            if r == rounds // 2:
                time.sleep(0.05)  # drain + park before the next burst

    threads = [
        threading.Thread(target=producer, args=(t,)) for t in range(8)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for t in range(8):
        for r in range(rounds):
            np.testing.assert_allclose(
                results[t][r], direct[t % 3], rtol=1e-6, atol=1e-7
            )
    assert b.stats["items"] == 8 * rounds


def test_abandon_then_resubmit_same_thread(models, monkeypatch, _fresh_plan):
    """Deadline-abandon then an immediate resubmit from the SAME thread:
    abandoning discards the thread's pooled completion waiter, so the
    dispatcher's late set() on the abandoned item (it was already inside
    the wedged device call) lands on an orphan Event and can never
    complete the thread's next item early with a missing result."""
    _set_plan(
        monkeypatch,
        [{"site": "serve_device_call", "times": 1, "error": "wedge",
          "seconds": 0.6}],
    )
    b = CrossModelBatcher(window_ms=0, max_batch=8, timeout_s=0.15)
    X = np.random.RandomState(13).rand(10, 4).astype(np.float32)
    with pytest.raises(TimeoutError):
        b.submit(models[0].spec_, models[0].params_, X)
    # the wedged call is still running; give the resubmit room to queue
    # behind it and outlive the late fan-out of the abandoned item
    b.timeout_s = 10.0
    out = b.submit(models[1].spec_, models[1].params_, X)
    np.testing.assert_allclose(
        out, models[1].predict(X), rtol=1e-6, atol=1e-7
    )


def test_deadline_in_scope_bounds_queue_wait(models, monkeypatch, _fresh_plan):
    """A request deadline (resilience scope) beats the batcher's own
    timeout and surfaces as DeadlineExceeded."""
    from gordo_tpu.observability import metrics as metric_catalog
    from gordo_tpu.server import resilience

    _set_plan(
        monkeypatch,
        [{"site": "serve_device_call", "times": 1, "error": "wedge",
          "seconds": 0.8}],
    )
    b = CrossModelBatcher(window_ms=0, max_batch=8, timeout_s=300)
    X = np.random.RandomState(8).rand(12, 4).astype(np.float32)
    before = metric_catalog.SERVER_DEADLINE_EXCEEDED.value(where="queue_wait")
    with resilience.request_scope(model="m-deadline", deadline_ms=150):
        with pytest.raises(resilience.DeadlineExceeded):
            b.submit(models[0].spec_, models[0].params_, X)
    assert (
        metric_catalog.SERVER_DEADLINE_EXCEEDED.value(where="queue_wait")
        == before + 1
    )


def test_fused_group_failure_isolates_poisoned_member(
    models, monkeypatch, _fresh_plan
):
    """The ladder, driven deterministically through _run_group: a group
    device-call failure bisects down to the poisoned member; the cohort's
    results are correct, only the poisoned item errors."""
    from gordo_tpu.observability import metrics as metric_catalog
    from gordo_tpu.ops.train import pad_for_predict
    from gordo_tpu.server.batcher import _Item
    from gordo_tpu.util import faults

    _set_plan(
        monkeypatch,
        [{"site": "serve_device_call", "machine": "m-poisoned",
          "times": -1, "error": "permanent"}],
    )
    b = CrossModelBatcher(window_ms=0, max_batch=8)
    X = np.random.RandomState(9).rand(30, 4).astype(np.float32)
    spec = models[0].spec_
    tags = ["m-ok-0", "m-poisoned", "m-ok-2"]
    items = []
    for model, tag in zip(models, tags):
        X_pad, n_pad, n_keep = pad_for_predict(spec, X)
        item = _Item(spec, model.params_, X_pad, n_pad, n_keep)
        item.t_submit = time.monotonic()
        item.tag = tag
        items.append(item)
    bisect_before = metric_catalog.GROUP_BISECTIONS.value()
    rescue_before = metric_catalog.GROUP_SERIAL_RESCUES.value()

    b._run_group(spec, items)

    assert all(item.done.is_set() for item in items)
    assert isinstance(items[1].error, faults.PermanentFault)
    for i in (0, 2):
        assert items[i].error is None
        # bisection re-runs survivors at a different vmap width than the
        # single-model predict; XLA does not promise bitwise-identical
        # float32 across batch shapes, so compare at the same tolerance
        # the auto-mode equivalence tests use (not 1e-6/1e-7, which flaked)
        np.testing.assert_allclose(
            items[i].result, models[i].predict(X), rtol=1e-5, atol=1e-6
        )
    # [ok, P, ok] -> bisect into [ok] and [P, ok] -> bisect into [P], [ok]
    # -> P's singleton serial rescue also faults; exactly 2 bisections
    assert metric_catalog.GROUP_BISECTIONS.value() == bisect_before + 2
    assert metric_catalog.GROUP_SERIAL_RESCUES.value() == rescue_before + 1


def test_nan_poisoned_lane_fails_alone_under_output_guard(
    models, monkeypatch, _fresh_plan
):
    """With the output guard on, a NaN input poisons only its own vmap
    lane: concurrent cohort submits through the REAL queue still succeed
    with correct results."""
    from gordo_tpu.util import faults

    monkeypatch.setenv("GORDO_TPU_VALIDATE_OUTPUT", "1")
    b = CrossModelBatcher(window_ms=60, max_batch=8)
    rng = np.random.RandomState(10)
    X_ok = rng.rand(24, 4).astype(np.float32)
    X_bad = X_ok.copy()
    X_bad[0, 0] = np.nan

    results, errors = {}, {}
    barrier = threading.Barrier(3)

    def run(i, X):
        barrier.wait()
        try:
            results[i] = b.submit(models[i].spec_, models[i].params_, X)
        except BaseException as exc:  # noqa: BLE001
            errors[i] = exc

    threads = [
        threading.Thread(target=run, args=(0, X_ok)),
        threading.Thread(target=run, args=(1, X_bad)),
        threading.Thread(target=run, args=(2, X_ok)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert isinstance(errors[1], faults.NonFiniteDataError)
    for i in (0, 2):
        np.testing.assert_allclose(
            results[i], models[i].predict(X_ok), rtol=1e-6, atol=1e-7
        )


# --------------------------------------------------- param-bank residency
def test_param_bank_lru_eviction_bounds_host_memory(monkeypatch):
    """Churning more models than the bank holds evicts LRU entries IN
    PLACE: host retention stays bounded (`trees` never exceeds the cap),
    surviving slots keep answering correctly, and an evicted model
    re-registers into a freed slot with correct results — no
    clear-everything reset, no stranded cohort."""
    from gordo_tpu.observability import metrics as metric_catalog

    monkeypatch.setenv("GORDO_TPU_PARAM_BANK_MAX", "4")
    b = CrossModelBatcher(window_ms=0, max_batch=8)
    fleet = [_fitted_autoencoder(seed) for seed in range(7)]
    rng = np.random.RandomState(3)
    X = rng.rand(16, 4).astype(np.float32)
    direct = [m.predict(X) for m in fleet]

    evictions_before = metric_catalog.PARAM_BANK_EVICTIONS.value()
    # churn well past capacity, twice over
    for _round in range(2):
        for i, m in enumerate(fleet):
            got = b.submit(m.spec_, m.params_, X)
            np.testing.assert_allclose(got, direct[i], rtol=1e-6, atol=1e-7)

    spec = fleet[0].spec_
    bank = b._banks[spec]
    assert len(bank.trees) <= 4
    assert len(bank.slots) <= 4
    assert metric_catalog.PARAM_BANK_EVICTIONS.value() > evictions_before
    # the retained pytrees are exactly the slot-resident ones (no ghost
    # references keeping evicted params alive)
    assert len(bank.trees) == len(bank.slots)

    # an evicted early model still predicts correctly after re-registering
    got = b.submit(fleet[0].spec_, fleet[0].params_, X)
    np.testing.assert_allclose(got, direct[0], rtol=1e-6, atol=1e-7)


def test_param_bank_register_params_prefills_slots(models):
    """Explicit registration (the warmup commit-once path) places params
    in the bank ahead of any submit; the subsequent batched predict finds
    its slot resident and returns correct values."""
    b = CrossModelBatcher(window_ms=0, max_batch=8)
    spec = models[0].spec_
    slots = [b.register_params(m.spec_, m.params_) for m in models]
    assert slots == [0, 1, 2]
    assert b.bank_size(spec) == 3
    # re-registration is idempotent
    assert b.register_params(models[1].spec_, models[1].params_) == 1

    rng = np.random.RandomState(4)
    X = rng.rand(12, 4).astype(np.float32)
    got = b.submit(models[2].spec_, models[2].params_, X)
    np.testing.assert_allclose(
        got, models[2].predict(X), rtol=1e-6, atol=1e-7
    )
    assert b.bank_size(spec) == 3  # submit registered nothing new


def test_warmup_preregisters_params_no_restack_at_first_traffic(
    model_collection_directory, trained_model_directories, monkeypatch
):
    """Satellite: warmup pre-registers every artifact's params into the
    batcher's param bank, so the first fused call of real traffic never
    restacks — asserted via the gordo_server_param_bank_* counters."""
    from gordo_tpu.observability import metrics as metric_catalog
    from gordo_tpu.server import warmup
    from gordo_tpu.server.utils import load_model

    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)

    result = warmup.warmup_collection(model_collection_directory)
    assert result["failed"] == []
    assert result["registered_params"] >= result["models"]

    b = batcher_mod.peek_batcher()
    assert b is not None
    assert sum(b.bank_size(spec) for spec in b._banks) >= result["models"]

    restacks_after_warmup = metric_catalog.PARAM_BANK_RESTACKS.value()
    # first post-warmup traffic: same artifacts, fresh submits
    rng = np.random.RandomState(5)
    for name in trained_model_directories:
        model = load_model(model_collection_directory, name)
        X = rng.rand(40, 4)
        model.predict(X)
    assert (
        metric_catalog.PARAM_BANK_RESTACKS.value() == restacks_after_warmup
    ), "post-warmup traffic restacked a param bank"


def test_warmup_aot_prelowers_zero_steady_state_trace_compiles(
    model_collection_directory, trained_model_directories, monkeypatch
):
    """Tentpole layer 3: warmup AOT pre-lowers the fused serving programs
    (``CrossModelBatcher.prelower``), so the first fused call of real
    traffic executes an already-compiled program —
    ``gordo_server_trace_compiles_total`` stays flat from the end of
    warmup onward."""
    from gordo_tpu.observability import metrics as metric_catalog
    from gordo_tpu.server import warmup
    from gordo_tpu.server.utils import load_model

    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)

    result = warmup.warmup_collection(model_collection_directory)
    assert result["failed"] == []
    assert result["aot_programs"] > 0

    b = batcher_mod.peek_batcher()
    assert b is not None
    assert b._aot, "warmup left no AOT executables behind"

    compiles_after_warmup = metric_catalog.TRACE_COMPILES.value()
    # steady state: bucket-shaped traffic (100 rows pads to the 128-row
    # warmup bucket) through every warmed artifact must not trace
    rng = np.random.RandomState(6)
    for name in trained_model_directories:
        model = load_model(model_collection_directory, name)
        X = rng.rand(100, 4)
        model.predict(X)
    assert (
        metric_catalog.TRACE_COMPILES.value() == compiles_after_warmup
    ), "post-warmup traffic paid a trace+compile in the serving path"


# ---------------------------------------------------------------------------
# Device-path pipelining (ISSUE 19): overlapped dispatch/drain must be
# byte-identical to the strict-serial path, and the loop must count overlaps.
# ---------------------------------------------------------------------------


def _make_item(model, X):
    from gordo_tpu.ops.train import pad_for_predict

    X_pad, n_pad, n_keep = pad_for_predict(model.spec_, X)
    item = batcher_mod._Item(
        model.spec_, model.params_, X_pad, n_pad, n_keep,
        done=threading.Event(),
    )
    item.t_submit = time.monotonic()
    return item


def test_pipeline_on_off_byte_parity(models, monkeypatch):
    """The same sequential workload through a pipelined and a
    strict-serial batcher produces byte-identical results (same program,
    same padding — only the host/device overlap differs)."""
    rng = np.random.RandomState(7)
    X = rng.rand(30, 4).astype(np.float32)

    monkeypatch.setenv("GORDO_TPU_DEVICE_PIPELINE", "0")
    serial = CrossModelBatcher(window_ms=0, max_batch=8)
    assert serial._pipeline is False
    got_serial = [serial.submit(m.spec_, m.params_, X) for m in models]

    monkeypatch.setenv("GORDO_TPU_DEVICE_PIPELINE", "1")
    piped = CrossModelBatcher(window_ms=0, max_batch=8)
    assert piped._pipeline is True
    got_piped = [piped.submit(m.spec_, m.params_, X) for m in models]

    for a, b in zip(got_serial, got_piped):
        np.testing.assert_array_equal(a, b)
    for got, m in zip(got_piped, models):
        np.testing.assert_allclose(got, m.predict(X), rtol=1e-6, atol=1e-7)
    assert piped.stats["items"] == len(models)


def test_two_outstanding_dispatches_drain_correctly(models):
    """White-box: two fused calls in flight at once (the double-buffered
    staging pair) drain to the same results the direct path computes —
    the second dispatch's buffer fill must not corrupt the first call."""
    b = CrossModelBatcher(window_ms=0, max_batch=8)
    rng = np.random.RandomState(8)
    X1 = rng.rand(25, 4).astype(np.float32)
    X2 = rng.rand(25, 4).astype(np.float32)
    i1 = _make_item(models[0], X1)
    i2 = _make_item(models[1], X2)

    p1 = b._run_async([i1])
    p2 = b._run_async([i2])  # dispatched while p1 is still undrained
    assert len(p1) == 1 and len(p2) == 1
    b._drain_call(p1[0])
    b._drain_call(p2[0])

    assert i1.error is None and i2.error is None
    np.testing.assert_allclose(
        i1.result, models[0].predict(X1), rtol=1e-6, atol=1e-7
    )
    np.testing.assert_allclose(
        i2.result, models[1].predict(X2), rtol=1e-6, atol=1e-7
    )
    assert b.stats["device_calls"] == 2


def test_pipeline_overlap_counter_counts_backed_up_ring(models):
    """Pre-load the ring before the dispatcher thread exists, then start
    it with max_batch=1: every call after the first is dispatched while
    its predecessor is still in flight — overlaps == n_items - 1."""
    b = CrossModelBatcher(window_ms=0, max_batch=1)
    assert b._pipeline is True
    rng = np.random.RandomState(9)
    X = rng.rand(10, 4).astype(np.float32)
    items = [_make_item(models[i % len(models)], X) for i in range(4)]
    for item in items:
        b._ring.put(item)
    b._ensure_thread()
    for item in items:
        assert item.done.wait(timeout=60), "pipelined loop never fanned out"
        assert item.error is None
    assert b.stats["pipeline_overlaps"] == len(items) - 1
    assert b.stats["device_calls"] == len(items)
    for i, item in enumerate(items):
        np.testing.assert_allclose(
            item.result,
            models[i % len(models)].predict(X),
            rtol=1e-6, atol=1e-7,
        )
