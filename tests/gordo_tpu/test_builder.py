import numpy as np
import pytest

from gordo_tpu.builder import ModelBuilder, local_build
from gordo_tpu.machine import Machine


def machine_config(name="test-model", cv_mode="full_build", epochs=1):
    return {
        "name": name,
        "dataset": {
            "type": "RandomDataset",
            "train_start_date": "2019-01-01T00:00:00+00:00",
            "train_end_date": "2019-01-05T00:00:00+00:00",
            "tags": ["tag-0", "tag-1", "tag-2"],
        },
        "model": {
            "sklearn.pipeline.Pipeline": {
                "steps": [
                    "sklearn.preprocessing.MinMaxScaler",
                    {
                        "gordo_tpu.models.models.AutoEncoder": {
                            "kind": "feedforward_hourglass",
                            "epochs": epochs,
                        }
                    },
                ]
            }
        },
        "evaluation": {"cv_mode": cv_mode},
        "project_name": "test-project",
    }


@pytest.fixture(scope="module")
def built():
    machine = Machine.from_config(machine_config(), project_name="test-project")
    return ModelBuilder(machine).build()


def test_build_returns_fitted_model(built):
    model, machine = built
    assert hasattr(model, "predict")
    out = model.predict(np.random.rand(10, 3))
    assert out.shape == (10, 3)


def test_build_metadata(built):
    _, machine = built
    md = machine.metadata.build_metadata
    assert md.model.model_offset == 0
    assert md.model.model_training_duration_sec > 0
    assert md.dataset.query_duration_sec > 0
    scores = md.model.cross_validation.scores
    assert "r2-score" in scores
    assert "r2-score-tag-0" in scores
    assert set(scores["r2-score"]) >= {"fold-mean", "fold-1", "fold-2", "fold-3"}
    splits = md.model.cross_validation.splits
    assert "fold-1-train-start" in splits


def test_cross_val_only_does_not_fit():
    machine = Machine.from_config(
        machine_config(cv_mode="cross_val_only"), project_name="test-project"
    )
    model, machine_out = ModelBuilder(machine).build()
    # model not fitted on full data: AutoEncoder deep in pipeline lacks params_
    ae = model.steps[-1][1]
    assert not hasattr(ae, "params_")
    assert machine_out.metadata.build_metadata.model.cross_validation.scores


def test_cache_key_deterministic():
    m1 = Machine.from_config(machine_config(), project_name="test-project")
    m2 = Machine.from_config(machine_config(), project_name="test-project")
    assert ModelBuilder(m1).cache_key == ModelBuilder(m2).cache_key
    m3 = Machine.from_config(machine_config(name="other-model"), project_name="x")
    assert ModelBuilder(m1).cache_key != ModelBuilder(m3).cache_key


def test_build_cache_roundtrip(tmp_path):
    machine = Machine.from_config(machine_config(), project_name="test-project")
    out1 = tmp_path / "out1"
    registry = tmp_path / "registry"
    builder = ModelBuilder(machine)
    model, machine_out = builder.build(output_dir=out1, model_register_dir=registry)
    assert (out1 / "model.pkl").exists()
    assert (out1 / "metadata.json").exists()

    # second build hits the cache
    out2 = tmp_path / "out2"
    builder2 = ModelBuilder(machine)
    assert builder2.check_cache(registry)
    model2, machine_out2 = builder2.build(output_dir=out2, model_register_dir=registry)
    user_defined = machine_out2.metadata.user_defined
    assert user_defined.get("build-metadata", {}).get("from_cache") is True

    # replace_cache busts it
    model3, machine_out3 = ModelBuilder(machine).build(
        output_dir=tmp_path / "out3", model_register_dir=registry, replace_cache=True
    )
    assert (
        machine_out3.metadata.user_defined.get("build-metadata", {}).get("from_cache")
        is not True
    )


def test_determine_offset():
    class FakeModel:
        def predict(self, X):
            return X[5:]

    assert ModelBuilder._determine_offset(FakeModel(), np.zeros((20, 2))) == 5


def test_metrics_from_list_default():
    funcs = ModelBuilder.metrics_from_list()
    names = [f.__name__ for f in funcs]
    assert "explained_variance_score" in names
    assert "r2_score" in names


def test_metrics_from_list_custom():
    funcs = ModelBuilder.metrics_from_list(
        ["sklearn.metrics.mean_absolute_error", "r2_score"]
    )
    assert funcs[0].__name__ == "mean_absolute_error"
    assert funcs[1].__name__ == "r2_score"


def test_local_build_yields_all(config_str):
    results = list(local_build(config_str))
    assert len(results) == 2
    for model, machine in results:
        assert hasattr(model, "anomaly")
        assert machine.metadata.build_metadata.model.model_meta


def test_seed_reproducibility():
    cfg = machine_config()
    cfg["evaluation"]["seed"] = 42
    m1 = Machine.from_config(cfg, project_name="p")
    model1, _ = ModelBuilder(m1).build()
    m2 = Machine.from_config(cfg, project_name="p")
    model2, _ = ModelBuilder(m2).build()
    X = np.random.RandomState(0).rand(20, 3)
    assert np.allclose(model1.predict(X), model2.predict(X))


def test_cache_hit_does_not_resave_onto_cached_artifact(tmp_path):
    """A cache-hit build whose destination IS the cached path must not
    rewrite the artifact: re-pickling in place risks corrupting a
    known-good entry and bakes the load-time from_cache marker into it."""
    machine = Machine.from_config(machine_config(), project_name="test-project")
    out = tmp_path / "out"
    reg = tmp_path / "reg"
    ModelBuilder(machine).build(output_dir=str(out), model_register_dir=str(reg))
    blob = (out / "model.pkl").read_bytes()
    mtime = (out / "model.pkl").stat().st_mtime_ns

    model, machine_out = ModelBuilder(machine).build(
        output_dir=str(out), model_register_dir=str(reg)
    )
    assert machine_out.metadata.user_defined.get("build-metadata", {}).get(
        "from_cache"
    )
    assert (out / "model.pkl").read_bytes() == blob
    assert (out / "model.pkl").stat().st_mtime_ns == mtime

    # a DIFFERENT destination still receives a copy and takes over the key
    out2 = tmp_path / "out2"
    ModelBuilder(machine).build(output_dir=str(out2), model_register_dir=str(reg))
    assert (out2 / "model.pkl").exists()
