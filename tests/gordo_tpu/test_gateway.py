"""
Gateway acceptance and unit tests (ISSUE 12).

The acceptance drive is the chaos test at the bottom: a 3-node
in-process fleet behind one :class:`GatewayServer` under open-loop load,
one node killed mid-storm through the ``node_dead`` fault site. The
contract being asserted is the issue's acceptance criteria verbatim:
requests for machines on healthy shards never fail, the killed shard is
served again (by its ring successor, via the hedged failover) within one
lease timeout, the gateway notices the death within the lease timeout
plus a poll tick, and the error rate over the whole storm stays bounded
— all observed through the gateway's own ``/metrics``.

The unit tests above it pin the pieces the chaos test composes: ring
determinism and minimal movement, lease staleness and generation
fencing, breaker state transitions, placement-key parsing, and the
``gateway_route`` injection site.
"""

import http.client
import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from types import SimpleNamespace

import pytest

from gordo_tpu.server import gateway, membership
from gordo_tpu.util import faults


# ------------------------------------------------------------------ ring
def test_ring_candidates_deterministic_and_distinct():
    ring = gateway.HashRing(vnodes=32)
    ring.rebuild(["node-a", "node-b", "node-c"])
    for key in ("m-001", "m-002", "some/path"):
        order = ring.candidates(key)
        assert sorted(order) == ["node-a", "node-b", "node-c"]
        assert order == ring.candidates(key)  # stable across calls
    assert ring.candidates("m-001", limit=2) == ring.candidates("m-001")[:2]


def test_ring_share_sums_to_one_and_tracks_vnodes():
    ring = gateway.HashRing(vnodes=64)
    ring.rebuild(["node-a", "node-b", "node-c"])
    share = ring.share()
    assert set(share) == {"node-a", "node-b", "node-c"}
    assert sum(share.values()) == pytest.approx(1.0)
    # vnode weighting keeps occupancy roughly balanced
    assert all(0.1 < s < 0.7 for s in share.values())


def test_ring_minimal_movement_on_node_loss():
    """Removing one node must only move the keys it owned — every other
    key keeps its primary (and therefore its node-side caches)."""
    keys = [f"m-{i:03d}" for i in range(200)]
    ring = gateway.HashRing(vnodes=64)
    ring.rebuild(["node-a", "node-b", "node-c"])
    before = {k: ring.candidates(k)[0] for k in keys}
    ring.rebuild(["node-a", "node-c"])
    after = {k: ring.candidates(k)[0] for k in keys}
    for key in keys:
        if before[key] != "node-b":
            assert after[key] == before[key]
        else:
            assert after[key] in ("node-a", "node-c")


def test_empty_ring_has_no_candidates():
    ring = gateway.HashRing(vnodes=8)
    assert ring.candidates("m-001") == []
    assert ring.share() == {}


# ------------------------------------------------------------ membership
def test_membership_register_heartbeat_withdraw(tmp_path, monkeypatch):
    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "2.0")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.1")
    view = membership.MembershipView(str(tmp_path))
    reg = membership.NodeRegistration(
        str(tmp_path), address="127.0.0.1:5555", node_id="node-a"
    )
    try:
        nodes = view.poll()
        assert nodes["node-a"].alive
        assert nodes["node-a"].address == "127.0.0.1:5555"
        assert nodes["node-a"].host == "127.0.0.1"
        assert nodes["node-a"].port == 5555
        assert [n.node_id for n in view.live_nodes()] == ["node-a"]
    finally:
        reg.close()
    # graceful leave withdraws the file: gone on the next poll, no
    # lease-timeout wait
    assert "node-a" not in view.poll()


def test_membership_stale_lease_is_dead(tmp_path, monkeypatch):
    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "0.4")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.1")
    view = membership.MembershipView(str(tmp_path))
    reg = membership.NodeRegistration(
        str(tmp_path), address="127.0.0.1:5555", node_id="node-a"
    )
    try:
        assert view.poll()["node-a"].alive
        # stop the heartbeat WITHOUT withdrawing (the kill -9 shape):
        # the file stays but its mtime goes stale
        reg._stop.set()
        reg._thread.join(timeout=2.0)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            info = view.poll().get("node-a")
            if info is not None and not info.alive:
                break
            time.sleep(0.05)
        info = view.poll()["node-a"]
        assert not info.alive
        assert info.age_s > 0.4
        assert view.live_nodes() == []
    finally:
        reg.close()


def test_membership_generation_fencing(tmp_path, monkeypatch):
    """A restarted twin takes generation+1; the old holder sees itself
    superseded and stops heartbeating, and readers follow the newest
    generation's address."""
    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "5.0")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.1")
    view = membership.MembershipView(str(tmp_path))
    old = membership.NodeRegistration(
        str(tmp_path), address="127.0.0.1:1111", node_id="node-a"
    )
    new = membership.NodeRegistration(
        str(tmp_path), address="127.0.0.1:2222", node_id="node-a"
    )
    try:
        assert new.generation == old.generation + 1
        assert not old.still_current()
        assert new.still_current()
        info = view.poll()["node-a"]
        assert info.generation == new.generation
        assert info.address == "127.0.0.1:2222"
        # the fenced holder's heartbeat thread exits on its own
        old._thread.join(timeout=2.0)
        assert not old._thread.is_alive()
    finally:
        new.close()
        old.close()


def test_membership_tolerates_stray_files(tmp_path):
    nodes_dir = tmp_path / "nodes"
    nodes_dir.mkdir()
    (nodes_dir / "not-a-lease").write_text("junk")
    (nodes_dir / "half-written.g2").write_text("{truncated")
    view = membership.MembershipView(str(tmp_path))
    assert view.poll() == {}


# --------------------------------------------------------------- breaker
def test_breaker_opens_on_consecutive_transients_and_half_opens():
    breaker = gateway.NodeBreaker("node-a", threshold=2, cooldown_s=0.2)
    assert breaker.allow()
    breaker.record_failure(faults.TransientFault("connect refused"))
    assert breaker.allow()  # below threshold
    breaker.record_failure(faults.TransientFault("connect refused"))
    assert not breaker.allow()  # open
    time.sleep(0.25)
    assert breaker.allow()  # half-open: exactly one probe
    assert not breaker.allow()  # the second concurrent probe is denied
    breaker.record_success()
    assert breaker.allow()  # closed again


def test_breaker_permanent_fault_opens_immediately():
    breaker = gateway.NodeBreaker("node-a", threshold=3, cooldown_s=60.0)
    breaker.record_failure(faults.PermanentFault("poisoned"))
    assert not breaker.allow()


def test_breaker_disabled_with_zero_threshold():
    breaker = gateway.NodeBreaker("node-a", threshold=0, cooldown_s=60.0)
    for _ in range(10):
        breaker.record_failure(faults.TransientFault("x"))
    assert breaker.allow()


# --------------------------------------------------------- placement key
@pytest.mark.parametrize(
    "path,expected",
    [
        ("/gordo/v0/proj/machine-1/prediction", ("machine-1", "proj")),
        ("/gordo/v0/proj/machine-1/anomaly/prediction",
         ("machine-1", "proj")),
        ("/gordo/v0/proj/machine-1/metadata", ("machine-1", "proj")),
        ("/gordo/v0/proj/models/", (None, "proj")),
        ("/gordo/v0/proj/revisions/", (None, "proj")),
        ("/healthcheck", (None, None)),
        ("/metrics", (None, None)),
    ],
)
def test_placement_key(path, expected, tmp_path):
    server = _make_gateway(tmp_path)
    try:
        assert server._placement_key(path) == expected
    finally:
        server.server_close()


# ------------------------------------------------------- 3-node fixture
class _StubNode:
    """One fake serving node: an HTTP server answering every route with
    its own id, plus a real membership lease. ``kill()`` (the
    ``node_dead`` on_dead callback) stops the HTTP server and closes the
    listener so new connects are refused — the in-process kill -9."""

    def __init__(self, directory: str, node_id: str):
        self.node_id = node_id
        self.hits = 0
        self._conns = set()
        self._conns_lock = threading.Lock()
        node = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def setup(self):
                super().setup()
                with node._conns_lock:
                    node._conns.add(self.connection)

            def finish(self):
                with node._conns_lock:
                    node._conns.discard(self.connection)
                super().finish()

            def _answer(self):
                node.hits += 1
                body = json.dumps(
                    {"node": node.node_id, "path": self.path}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_GET = do_POST = _answer

            def log_message(self, *args):  # silence
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.thread.start()
        self.registration = membership.NodeRegistration(
            directory,
            address=f"127.0.0.1:{self.port}",
            node_id=node_id,
            on_dead=self.kill,
        )

    def kill(self):
        # a real kill -9 takes the listener AND every established
        # keep-alive connection with it — sever both, or the gateway's
        # pooled upstream connections would keep being served by a ghost
        self.httpd.shutdown()
        self.httpd.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        self.registration.close()
        self.kill()
        self.thread.join(timeout=2.0)


def _make_gateway(tmp_path) -> gateway.GatewayServer:
    return gateway.GatewayServer(str(tmp_path), host="127.0.0.1", port=0)


def _gateway_request(server, method, path, headers=None, timeout=10):
    conn = http.client.HTTPConnection(
        "127.0.0.1", server.server_port, timeout=timeout
    )
    try:
        conn.request(method, path, headers=headers or {})
        resp = conn.getresponse()
        data = resp.read()
        return resp.status, {k.lower(): v for k, v in resp.getheaders()}, data
    finally:
        conn.close()


@pytest.fixture
def fleet(tmp_path, monkeypatch):
    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "2.5")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.2")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_HEALTH_S", "0.3")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_CONNECT_TIMEOUT_S", "0.5")
    faults.reset_plan()
    nodes = [_StubNode(str(tmp_path), f"node-{c}") for c in "abc"]
    server = _make_gateway(tmp_path)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    deadline = time.monotonic() + 5.0
    while len(server.ring.nodes) < 3 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert len(server.ring.nodes) == 3
    yield SimpleNamespace(server=server, nodes=nodes)
    monkeypatch.delenv(faults.PLAN_ENV, raising=False)
    faults.reset_plan()
    server.shutdown()
    server.server_close()
    thread.join(timeout=5)
    for node in nodes:
        node.close()


# --------------------------------------------------------- routed basics
def test_gateway_routes_by_ring_placement(fleet):
    """A machine's requests always land on its ring primary, reported in
    X-Gordo-Gateway-Node and visible in the stub's answer."""
    server = fleet.server
    for i in range(6):
        machine = f"m-{i:03d}"
        primary = server.ring.candidates(machine)[0]
        status, headers, body = _gateway_request(
            server, "GET", f"/gordo/v0/proj/{machine}/metadata"
        )
        assert status == 200
        assert headers["x-gordo-gateway-node"] == primary
        assert json.loads(body)["node"] == primary


def test_gateway_local_endpoints(fleet):
    server = fleet.server
    status, _, body = _gateway_request(server, "GET", "/healthcheck")
    assert status == 200
    assert json.loads(body)["nodes"] == 3

    status, _, body = _gateway_request(server, "GET", "/gateway/status")
    assert status == 200
    doc = json.loads(body)
    assert set(doc["nodes"]) == {"node-a", "node-b", "node-c"}
    assert sum(doc["ring"]["share"].values()) == pytest.approx(1.0)

    status, headers, body = _gateway_request(server, "GET", "/metrics")
    assert status == 200
    assert "text/plain" in headers["content-type"]
    assert b"gordo_gateway_requests_total" in body


def test_gateway_no_live_nodes_is_503_retry_after(tmp_path, monkeypatch):
    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "2.0")
    server = _make_gateway(tmp_path)  # empty membership dir
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        status, headers, body = _gateway_request(
            server, "GET", "/gordo/v0/proj/m-001/metadata"
        )
        assert status == 503
        assert int(headers["retry-after"]) >= 1
        assert "no live serving nodes" in json.loads(body)["error"]
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=5)


def test_gateway_route_fault_injection(fleet, monkeypatch):
    """The gateway_route site: an injected transient answers 503 with
    Retry-After before any upstream is touched; the next request (rule
    exhausted) routes normally."""
    server = fleet.server
    monkeypatch.setenv(
        faults.PLAN_ENV,
        json.dumps(
            {
                "rules": [
                    {
                        "site": "gateway_route",
                        "machine": "m-001",
                        "times": 1,
                        "error": "transient",
                    }
                ]
            }
        ),
    )
    faults.reset_plan()
    status, headers, _ = _gateway_request(
        server, "GET", "/gordo/v0/proj/m-001/metadata"
    )
    assert status == 503
    assert headers.get("retry-after")
    status, _, _ = _gateway_request(
        server, "GET", "/gordo/v0/proj/m-001/metadata"
    )
    assert status == 200


def test_gateway_node_partition_hedges_to_successor(fleet, monkeypatch):
    """The node_partition site: a transient on the primary's connect path
    spends the hedge on the next ring replica — the client still gets a
    200, answered by the successor."""
    server = fleet.server
    machine = "m-007"
    order = server.ring.candidates(machine)
    monkeypatch.setenv(
        faults.PLAN_ENV,
        json.dumps(
            {
                "rules": [
                    {
                        "site": "node_partition",
                        "machine": order[0],
                        "times": 1,
                        "error": "transient",
                    }
                ]
            }
        ),
    )
    faults.reset_plan()
    status, headers, body = _gateway_request(
        server, "GET", f"/gordo/v0/proj/{machine}/metadata"
    )
    assert status == 200
    assert headers["x-gordo-gateway-node"] == order[1]
    assert json.loads(body)["node"] == order[1]


# -------------------------------------------------------------- chaos
def test_chaos_kill_one_node_healthy_shards_unharmed(fleet, monkeypatch):
    """The acceptance drive (ISSUE 12): open-loop load over a 3-node
    fleet, one node killed through the node_dead fault site mid-storm.

    Asserted, per the issue's acceptance criteria:
    - requests for machines on healthy shards NEVER fail;
    - the killed shard keeps being served (hedged failover to the ring
      successor) — first post-kill success within one lease timeout;
    - the gateway's membership view drops the dead node within the lease
      timeout plus a heartbeat + health-poll tick;
    - the error rate over the killed shard is bounded, asserted from the
      gateway's merged /metrics.
    """
    server = fleet.server
    lease_timeout = 2.5

    machines = [f"m-{i:03d}" for i in range(60)]
    primaries = {m: server.ring.candidates(m)[0] for m in machines}
    kill_node = primaries[machines[0]]
    victims = [m for m in machines if primaries[m] == kill_node][:4]
    healthy = [m for m in machines if primaries[m] != kill_node][:4]
    assert victims and healthy

    failover_before = sum(
        dict(gateway.metric_catalog.GATEWAY_FAILOVERS.snapshot()).values()
    )

    results = []  # (t, machine, status, serving_node)
    t_kill = None
    t_detect = None
    t0 = time.monotonic()
    deadline = t0 + 12.0
    i = 0
    while time.monotonic() < deadline:
        machine = (victims + healthy)[i % (len(victims) + len(healthy))]
        i += 1
        try:
            status, headers, _ = _gateway_request(
                server, "GET", f"/gordo/v0/proj/{machine}/metadata",
                timeout=5,
            )
            node = headers.get("x-gordo-gateway-node", "")
        except OSError:
            status, node = -1, ""
        results.append((time.monotonic() - t0, machine, status, node))

        if t_kill is None and i >= 20:
            monkeypatch.setenv(
                faults.PLAN_ENV,
                json.dumps(
                    {
                        "rules": [
                            {
                                "site": "node_dead",
                                "machine": kill_node,
                                "times": 1,
                                "error": "transient",
                            }
                        ]
                    }
                ),
            )
            faults.reset_plan()
            t_kill = time.monotonic() - t0
        if t_kill is not None and t_detect is None:
            if kill_node not in server._live:
                t_detect = time.monotonic() - t0
        if t_detect is not None and time.monotonic() - t0 > t_detect + 1.0:
            break
        time.sleep(0.015)

    assert t_kill is not None
    # membership noticed the death: stale lease dropped within the lease
    # timeout plus a heartbeat interval and a couple of health-poll ticks
    assert t_detect is not None, "gateway never noticed the dead node"
    assert t_detect - t_kill <= lease_timeout + 1.5

    healthy_results = [r for r in results if r[1] in healthy]
    victim_results = [r for r in results if r[1] in victims]
    assert healthy_results and victim_results

    # healthy shards: zero failures, before and after the kill
    assert all(r[2] == 200 for r in healthy_results), [
        r for r in healthy_results if r[2] != 200
    ]

    # killed shard: served again within one lease timeout of the kill
    # (in practice immediately, via the hedged failover)
    post_kill_ok = [
        r for r in victim_results if r[0] > t_kill and r[2] == 200
    ]
    assert post_kill_ok, "killed shard never recovered"
    assert post_kill_ok[0][0] - t_kill <= lease_timeout
    # ... and by the end it is served by a surviving node
    tail = victim_results[-3:]
    assert all(r[2] == 200 and r[3] != kill_node for r in tail), tail

    # bounded error rate over the storm: only the brief window between
    # the kill and the breaker/hedge taking over may fail
    errors = [r for r in results if r[2] != 200]
    assert len(errors) <= max(3, len(results) // 10), errors

    # observed through the gateway's own merged /metrics
    status, _, metrics_body = _gateway_request(server, "GET", "/metrics")
    assert status == 200
    text = metrics_body.decode()
    assert "gordo_gateway_requests_total" in text
    assert "gordo_gateway_failovers_total" in text
    failover_after = sum(
        dict(gateway.metric_catalog.GATEWAY_FAILOVERS.snapshot()).values()
    )
    assert failover_after > failover_before


# -------------------------------------------- lease expiry edge cases
def _newest_lease_path(directory: str, node_id: str) -> str:
    nodes_dir = os.path.join(directory, "nodes")
    candidates = sorted(
        name for name in os.listdir(nodes_dir)
        if name.startswith(f"{node_id}.g")
    )
    assert candidates, f"no lease file for {node_id}"
    return os.path.join(nodes_dir, candidates[-1])


def _storm(server, machines, seconds):
    """Round-robin requests over ``machines``; returns [(machine, status,
    serving_node)] — transport errors recorded as status -1."""
    results = []
    deadline = time.monotonic() + seconds
    i = 0
    while time.monotonic() < deadline:
        machine = machines[i % len(machines)]
        i += 1
        try:
            status, headers, _ = _gateway_request(
                server, "GET", f"/gordo/v0/proj/{machine}/metadata",
                timeout=5,
            )
            node = headers.get("x-gordo-gateway-node", "")
        except OSError:
            status, node = -1, ""
        results.append((machine, status, node))
        time.sleep(0.02)
    return results


def test_gateway_corrupted_lease_self_heals_no_5xx(fleet):
    """A lease file overwritten with garbage mid-routing: the owner's
    heartbeat (mkstemp + os.replace) restores a valid payload within one
    beat, and meanwhile NO request — healthy shards or the victim's —
    sees a 5xx: the victim either keeps routing (poll skips the corrupt
    file only until the next beat) or hedges to its ring successor."""
    server = fleet.server
    directory = fleet.nodes[0].registration.directory
    victim = "node-b"
    lease = _newest_lease_path(directory, victim)

    with open(lease, "w") as fh:
        fh.write("\x00garbage{not json")

    machines = [f"m-{i:03d}" for i in range(12)]
    results = _storm(server, machines, seconds=1.5)
    assert results
    assert all(r[1] == 200 for r in results), [r for r in results if r[1] != 200]

    # the heartbeat healed the file: valid payload, correct address
    deadline = time.monotonic() + 2.0
    payload = None
    while time.monotonic() < deadline:
        try:
            with open(_newest_lease_path(directory, victim)) as fh:
                payload = json.load(fh)
            break
        except (OSError, ValueError):
            time.sleep(0.05)
    assert payload is not None, "corrupted lease never healed"
    assert payload["node_id"] == victim
    # ... and the gateway still (or again) sees the full fleet
    deadline = time.monotonic() + 2.0
    while len(server.ring.nodes) < 3 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert len(server.ring.nodes) == 3


def test_gateway_deleted_lease_self_heals_no_5xx(fleet):
    """A lease file deleted outright (operator fat-finger, janitor bug):
    same contract as corruption — the heartbeat's os.replace recreates
    the file within one beat, zero 5xx throughout, ring back to full
    strength within one refresh interval."""
    server = fleet.server
    directory = fleet.nodes[0].registration.directory
    victim = "node-c"
    os.unlink(_newest_lease_path(directory, victim))

    machines = [f"m-{i:03d}" for i in range(12)]
    results = _storm(server, machines, seconds=1.5)
    assert results
    assert all(r[1] == 200 for r in results), [r for r in results if r[1] != 200]

    # heartbeat recreated the lease and the gateway converged on 3 nodes
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        nodes_dir = os.path.join(directory, "nodes")
        back = any(
            name.startswith(f"{victim}.g") for name in os.listdir(nodes_dir)
        )
        if back and len(server.ring.nodes) == 3:
            break
        time.sleep(0.05)
    assert any(
        name.startswith(f"{victim}.g")
        for name in os.listdir(os.path.join(directory, "nodes"))
    ), "deleted lease never recreated"
    assert len(server.ring.nodes) == 3


def test_gateway_stale_orphan_lease_never_attracts_traffic(fleet):
    """A stale-mtime lease for a node that no longer exists (crashed
    before withdrawing, beyond the lease timeout): the gateway must treat
    it as dead — it never joins the ring, never serves a request, and
    healthy shards see zero 5xx while it sits there."""
    server = fleet.server
    directory = fleet.nodes[0].registration.directory
    nodes_dir = os.path.join(directory, "nodes")
    ghost = os.path.join(nodes_dir, "node-ghost.g1")
    with open(ghost, "w") as fh:
        fh.write(json.dumps({
            "node_id": "node-ghost",
            # a port nothing listens on: routing here would be a 5xx
            "address": "127.0.0.1:1",
            "pid": 0,
            "ts": time.time() - 86400.0,
        }))
    os.utime(ghost, (time.time() - 86400.0, time.time() - 86400.0))

    # let several health polls pass, then storm
    time.sleep(0.8)
    machines = [f"m-{i:03d}" for i in range(12)]
    results = _storm(server, machines, seconds=1.2)
    assert results
    assert all(r[1] == 200 for r in results), [r for r in results if r[1] != 200]
    assert all(r[2] != "node-ghost" for r in results)
    assert "node-ghost" not in server.ring.nodes
    assert "node-ghost" not in server._live


# ------------------------------------------------ Unix-domain lane (ISSUE 19)
def test_membership_uds_round_trip(tmp_path, monkeypatch):
    """A node's advertised UDS path survives the lease write -> poll
    round trip; nodes that advertise none read back as None."""
    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "2.0")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.1")
    view = membership.MembershipView(str(tmp_path))
    sock_path = str(tmp_path / "node-a.sock")
    with_uds = membership.NodeRegistration(
        str(tmp_path), address="127.0.0.1:5555", node_id="node-a",
        uds=sock_path,
    )
    without = membership.NodeRegistration(
        str(tmp_path), address="127.0.0.1:5556", node_id="node-b"
    )
    try:
        nodes = view.poll()
        assert nodes["node-a"].uds == sock_path
        assert nodes["node-b"].uds is None
    finally:
        with_uds.close()
        without.close()


def _tiny_wsgi_app(environ, start_response):
    body = json.dumps(
        {"node": "uds-only", "path": environ["PATH_INFO"]}
    ).encode()
    start_response(
        "200 OK",
        [("Content-Type", "application/json"),
         ("Content-Length", str(len(body)))],
    )
    return [body]


def test_gateway_routes_over_advertised_uds(tmp_path, monkeypatch):
    """The gateway dials a co-located node's advertised Unix-domain
    socket: the node's lease names a TCP address nothing listens on, so
    the 200 can only have traveled the UDS lane."""
    from gordo_tpu.server import fastlane

    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "2.5")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.2")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_HEALTH_S", "5.0")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_CONNECT_TIMEOUT_S", "0.5")
    sock_path = str(tmp_path / "node-uds.sock")
    node = fastlane.EventLoopServer(
        _tiny_wsgi_app, host="127.0.0.1", port=0, uds=sock_path
    )
    node_thread = threading.Thread(target=node.serve_forever, daemon=True)
    node_thread.start()
    registration = membership.NodeRegistration(
        str(tmp_path), address="127.0.0.1:1",  # dead TCP: UDS or bust
        node_id="node-uds", uds=sock_path,
    )
    server = _make_gateway(tmp_path)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        deadline = time.monotonic() + 5.0
        while not server.ring.nodes and time.monotonic() < deadline:
            time.sleep(0.05)
        status, headers, body = _gateway_request(
            server, "GET", "/gordo/v0/proj/m-001/metadata"
        )
        assert status == 200, body[:300]
        assert headers["x-gordo-gateway-node"] == "node-uds"
        assert json.loads(body)["node"] == "uds-only"
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=5)
        registration.close()
        node.server_close()
        node_thread.join(timeout=5)


def test_gateway_falls_back_to_tcp_on_stale_uds(tmp_path, monkeypatch):
    """A stale advertised socket path (node restarted without its UDS
    lane) is not a node failure: the gateway retries the same node over
    its TCP address before spending a hedge."""
    from gordo_tpu.server import fastlane

    monkeypatch.setenv(membership.LEASE_TIMEOUT_ENV, "2.5")
    monkeypatch.setenv(membership.HEARTBEAT_ENV, "0.2")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_HEALTH_S", "5.0")
    monkeypatch.setenv("GORDO_TPU_GATEWAY_CONNECT_TIMEOUT_S", "0.5")
    node = fastlane.EventLoopServer(
        _tiny_wsgi_app, host="127.0.0.1", port=0, uds=""
    )
    node_thread = threading.Thread(target=node.serve_forever, daemon=True)
    node_thread.start()
    # advertise a path that EXISTS (so the gateway prefers it) but that
    # nothing serves — a socket file with no listener behind it
    stale = tmp_path / "stale.sock"
    orphan = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    orphan.bind(str(stale))
    orphan.close()  # closed without listen(): connects fail, file stays
    registration = membership.NodeRegistration(
        str(tmp_path), address=f"127.0.0.1:{node.server_port}",
        node_id="node-tcp", uds=str(stale),
    )
    server = _make_gateway(tmp_path)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        deadline = time.monotonic() + 5.0
        while not server.ring.nodes and time.monotonic() < deadline:
            time.sleep(0.05)
        status, headers, body = _gateway_request(
            server, "GET", "/gordo/v0/proj/m-001/metadata"
        )
        assert status == 200, body[:300]
        assert headers["x-gordo-gateway-node"] == "node-tcp"
    finally:
        server.shutdown()
        server.server_close()
        thread.join(timeout=5)
        registration.close()
        node.server_close()
        node_thread.join(timeout=5)
