"""
Docker-backed integration tests: the real-protocol seams.

Reference parity: tests/conftest.py:270-332 spins up influxdb 1.7 and
postgres 11 containers (auto-marked ``dockertest``) so the Influx provider
and Postgres reporter are exercised against real wire protocols, not fakes.
These run the same way — marked ``dockertest`` and EXCLUDED from the
default run (pytest.ini addopts ``-m "not dockertest"``); run them with
``pytest -m dockertest tests/gordo_tpu/test_dockertest.py``.

Container management uses the docker CLI via subprocess (no docker-py
dependency); each test skips cleanly when docker (or the postgres driver)
is not available on the host.
"""

import shutil
import subprocess
import uuid

import numpy as np
import pytest
import requests

pytestmark = pytest.mark.dockertest

_HAS_DOCKER = shutil.which("docker") is not None


from _nethelpers import free_port as _free_port  # noqa: E402
from _nethelpers import wait_for as _wait_for  # noqa: E402


def _docker_run(image: str, name: str, ports: dict, env: dict) -> str:
    """Start a detached container, or SKIP the test: an installed docker
    CLI with a stopped daemon, no network to pull the image, or an
    allocated port are environment problems, not failures."""
    cmd = ["docker", "run", "--rm", "-d", "--name", name]
    for host, cont in ports.items():
        cmd += ["-p", f"{host}:{cont}"]
    for key, value in env.items():
        cmd += ["-e", f"{key}={value}"]
    cmd.append(image)
    out = subprocess.run(cmd, capture_output=True, text=True)
    if out.returncode != 0:
        pytest.skip(f"docker run {image} failed: {out.stderr.strip()[:200]}")
    return out.stdout.strip()


def _docker_kill(name: str) -> None:
    subprocess.run(["docker", "kill", name], capture_output=True)




@pytest.fixture(scope="module")
def influxdb():
    if not _HAS_DOCKER:
        pytest.skip("docker CLI not available")
    name = f"gordo-tpu-influx-{uuid.uuid4().hex[:8]}"
    port = _free_port()
    _docker_run(
        "influxdb:1.7-alpine",
        name,
        ports={port: 8086},
        env={
            "INFLUXDB_DB": "gordo",
            "INFLUXDB_ADMIN_USER": "admin",
            "INFLUXDB_ADMIN_PASSWORD": "pass",
        },
    )
    base = f"http://localhost:{port}"
    try:
        if not _wait_for(
            lambda: requests.get(f"{base}/ping", timeout=2).status_code == 204
        ):
            pytest.skip("influxdb container failed to become ready")
        yield base
    finally:
        _docker_kill(name)


@pytest.fixture(scope="module")
def postgresdb():
    if not _HAS_DOCKER:
        pytest.skip("docker CLI not available")
    psycopg2 = pytest.importorskip("psycopg2")
    name = f"gordo-tpu-pg-{uuid.uuid4().hex[:8]}"
    port = _free_port()
    _docker_run(
        "postgres:11-alpine",
        name,
        ports={port: 5432},
        env={"POSTGRES_USER": "postgres", "POSTGRES_PASSWORD": "postgres"},
    )

    def _ping():
        conn = psycopg2.connect(
            host="localhost", port=port, user="postgres",
            password="postgres", dbname="postgres", connect_timeout=2,
        )
        conn.close()
        return True

    try:
        if not _wait_for(_ping):
            pytest.skip("postgres container failed to become ready")
        yield {"host": "localhost", "port": port}
    finally:
        _docker_kill(name)


def _write_influx_points(base: str, tag: str, values, start_ns: int, step_ns: int):
    """Raw line-protocol writes — the same wire format the client's influx
    forwarder emits."""
    lines = "\n".join(
        f"sensors,tag={tag} Value={v} {start_ns + i * step_ns}"
        for i, v in enumerate(values)
    )
    resp = requests.post(
        f"{base}/write", params={"db": "gordo", "precision": "ns"},
        data=lines.encode(), auth=("admin", "pass"), timeout=5,
    )
    assert resp.status_code == 204, resp.text


def test_influx_provider_roundtrip_real_influxql(influxdb):
    """InfluxDataProvider reads back, over real InfluxQL-over-HTTP, exactly
    the series a line-protocol writer put in."""
    import dateutil.parser

    from gordo_tpu.dataset.data_provider import InfluxDataProvider
    from gordo_tpu.dataset.sensor_tag import SensorTag

    start = dateutil.parser.isoparse("2019-01-01T00:00:00+00:00")
    start_ns = int(start.timestamp() * 1e9)
    step_ns = 600 * int(1e9)  # 10 min
    values = np.round(np.random.RandomState(0).rand(24), 6)
    _write_influx_points(influxdb, "dock-tag-0", values, start_ns, step_ns)

    provider = InfluxDataProvider(
        uri=f"{influxdb}/gordo", username="admin", password="pass"
    )
    end = dateutil.parser.isoparse("2019-01-02T00:00:00+00:00")
    series = list(
        provider.load_series(start, end, [SensorTag("dock-tag-0", "asset")])
    )
    assert len(series) == 1
    got = series[0]
    assert len(got) == len(values)
    np.testing.assert_allclose(got.to_numpy(), values, rtol=1e-6)


def test_influx_provider_empty_range(influxdb):
    import dateutil.parser

    from gordo_tpu.dataset.data_provider import InfluxDataProvider
    from gordo_tpu.dataset.sensor_tag import SensorTag

    provider = InfluxDataProvider(
        uri=f"{influxdb}/gordo", username="admin", password="pass"
    )
    series = list(
        provider.load_series(
            dateutil.parser.isoparse("2030-01-01T00:00:00+00:00"),
            dateutil.parser.isoparse("2030-01-02T00:00:00+00:00"),
            [SensorTag("dock-tag-0", "asset")],
        )
    )
    assert all(len(s) == 0 for s in series)


def test_postgres_reporter_real_upsert(postgresdb):
    """PostgresReporter against a real postgres: create-table, insert, and
    the ON CONFLICT upsert path with genuine psycopg2 %s paramstyle."""
    import psycopg2

    from gordo_tpu.machine import Machine
    from gordo_tpu.reporters.postgres import PostgresReporter

    machine = Machine.from_config(
        {
            "name": "dock-machine",
            "dataset": {
                "type": "RandomDataset",
                "tags": ["dt-0", "dt-1"],
                "train_start_date": "2019-01-01T00:00:00+00:00",
                "train_end_date": "2019-01-02T00:00:00+00:00",
            },
            "model": {
                "gordo_tpu.models.models.AutoEncoder": {
                    "kind": "feedforward_hourglass"
                }
            },
        },
        project_name="dockertest",
    )

    reporter = PostgresReporter(
        host=postgresdb["host"], port=postgresdb["port"],
        user="postgres", password="postgres", database="postgres",
    )
    reporter.report(machine)
    machine.metadata.user_defined["marker"] = "second-write"
    reporter.report(machine)  # upsert, not duplicate-key error

    conn = psycopg2.connect(
        host=postgresdb["host"], port=postgresdb["port"], user="postgres",
        password="postgres", dbname="postgres",
    )
    try:
        with conn.cursor() as cur:
            cur.execute("SELECT name, metadata FROM machine")
            rows = cur.fetchall()
    finally:
        conn.close()
    assert len(rows) == 1
    assert rows[0][0] == "dock-machine"
    assert "second-write" in rows[0][1]
