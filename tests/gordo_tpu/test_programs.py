"""
Build-to-serve compiled-artifact pipeline (ISSUE 14).

The contract under test: a build with ``GORDO_TPU_SHIP_PROGRAMS=1``
emits AOT-serialized fused serving executables into
``<artifact>/programs/`` with a host-fingerprinted manifest; serving
warmup with ``GORDO_TPU_LOAD_SHIPPED_PROGRAMS=1`` deserializes them into
the batcher's AOT cache before the first predict; and the fingerprint
ladder guarantees an artifact from a genuinely different host is
REJECTED loudly (counter + warning, jit fallback, byte-identical
responses) while a cosmetic ``prefer-no-gather``-style diff still loads.
The drift loop's hot swap rides the same loader, so a delta revision's
shipped programs are live before the pointer flips.
"""

import json
import os
import shutil

import numpy as np
import pytest

from gordo_tpu.builder.build_model import ModelBuilder
from gordo_tpu.machine import Machine
from gordo_tpu.observability import metrics as metric_catalog
from gordo_tpu.serializer import programs
from gordo_tpu.server import batcher as batcher_mod
from gordo_tpu.server import hotswap, warmup
from gordo_tpu.util import xla_cache

MACHINE_NAME = "prog-pipeline-m0"
N_TAGS = 4


def _machine_config(name):
    return {
        "name": name,
        "dataset": {
            "type": "RandomDataset",
            "train_start_date": "2017-12-25 06:00:00Z",
            "train_end_date": "2017-12-30 06:00:00Z",
            "tag_list": [f"tag-{i}" for i in range(N_TAGS)],
        },
        "model": {
            "gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector": {
                "base_estimator": {
                    "sklearn.pipeline.Pipeline": {
                        "steps": [
                            "sklearn.preprocessing.MinMaxScaler",
                            {
                                "gordo_tpu.models.models.AutoEncoder": {
                                    "kind": "feedforward_hourglass",
                                    "epochs": 1,
                                }
                            },
                        ]
                    }
                }
            }
        },
        "project_name": "test-programs",
    }


@pytest.fixture(scope="module")
def shipped_collection(tmp_path_factory):
    """One artifact built ONCE with program shipping on — the expensive
    part (train + compile + serialize) shared by every test here. Tests
    that tamper with the manifest copy the artifact first."""
    collection = tmp_path_factory.mktemp("shipped") / "rev-1"
    machine = Machine.from_config(
        _machine_config(MACHINE_NAME), project_name="test-programs"
    )
    os.environ["GORDO_TPU_SHIP_PROGRAMS"] = "1"
    try:
        ModelBuilder(machine).build(
            output_dir=str(collection / MACHINE_NAME)
        )
    finally:
        os.environ.pop("GORDO_TPU_SHIP_PROGRAMS", None)
    return str(collection)


@pytest.fixture
def fresh_batcher(monkeypatch):
    """Forced-on, process-fresh batcher (the test_batcher.py pattern)."""
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    yield
    monkeypatch.setattr(batcher_mod, "_batcher", None)


def _copy_collection(src_collection, tmp_path):
    dst = tmp_path / "rev-copy"
    shutil.copytree(src_collection, dst)
    return str(dst)


def _manifest(collection):
    path = programs.manifest_path(os.path.join(collection, MACHINE_NAME))
    with open(path) as fh:
        return json.load(fh), path


# ---------------------------------------------------------------- build side
def test_build_ships_manifest_and_programs(shipped_collection):
    manifest, path = _manifest(shipped_collection)
    assert manifest["schema_version"] == programs.MANIFEST_SCHEMA_VERSION
    assert manifest["fingerprint"] == xla_cache.host_fingerprint()
    assert manifest["platform"]  # the build's jax backend
    assert isinstance(manifest["cpu_features"], list)
    entries = manifest["programs"]
    # warmup row buckets (128, 1024) x fuse widths (1, 4, 16, 64)
    assert len(entries) == 8
    programs_dir = os.path.dirname(path)
    for entry in entries:
        assert os.path.isfile(os.path.join(programs_dir, entry["file"]))
        assert entry["capacity"] == 8  # fleet of 1 -> the bank's floor
        assert entry["compile_s"] >= 0


def test_ship_disabled_by_default(tmp_path):
    """Without the knob, the build must not grow a programs/ dir — the
    artifact contract is unchanged for every existing operator."""
    assert not programs.ship_enabled()
    machine = Machine.from_config(
        _machine_config("prog-noship"), project_name="test-programs"
    )
    out = tmp_path / "noship" / "prog-noship"
    ModelBuilder(machine).build(output_dir=str(out))
    assert not os.path.exists(out / "programs")


# ---------------------------------------------------------------- serve side
def test_warmup_loads_shipped_programs_without_compiling(
    shipped_collection, fresh_batcher, monkeypatch
):
    monkeypatch.setenv("GORDO_TPU_LOAD_SHIPPED_PROGRAMS", "1")
    report = warmup.warmup_collection(shipped_collection)
    assert report["failed"] == []
    assert report["aot_shipped"] == 8
    assert report["aot_rejected"] == 0
    assert report["compile_seconds_saved"] > 0
    # every AOT key came from deserialization; prelower found them all
    # present and compiled nothing
    assert report["aot_programs"] == 0
    batcher = batcher_mod.peek_batcher()
    assert batcher is not None
    assert len(batcher._aot) == 8
    assert batcher.aot_stats["shipped"] == 8
    assert batcher.aot_stats["compiled"] == 0
    # the report is surfaced for /debug/vars
    assert warmup.last_report()["aot_shipped"] == 8


def test_load_disabled_by_default_still_prelowers(
    shipped_collection, fresh_batcher
):
    """Knob unset: shipped programs are ignored and warmup compiles its
    own, exactly as before the pipeline existed."""
    assert not programs.load_enabled()
    report = warmup.warmup_collection(shipped_collection)
    assert report["failed"] == []
    assert report["aot_shipped"] == 0
    assert report["aot_programs"] > 0
    assert batcher_mod.peek_batcher().aot_stats["shipped"] == 0


# ------------------------------------------------------- fingerprint ladder
def test_classify_ladder_schema_platform_and_isa():
    import jax

    manifest, _ = (
        {
            "schema_version": programs.MANIFEST_SCHEMA_VERSION,
            "fingerprint": xla_cache.host_fingerprint(),
            "platform": jax.default_backend(),
            "machine": __import__("platform").machine(),
            "cpu_features": sorted(xla_cache.host_cpu_features()),
            "jaxlib": __import__("jaxlib").__version__,
        },
        None,
    )
    assert programs.classify_manifest(manifest) == ("match", "")

    schema = dict(manifest, schema_version=99)
    status, reason = programs.classify_manifest(schema)
    assert status == "rejected" and "schema" in reason

    platform_diff = dict(manifest, platform="tpu")
    status, reason = programs.classify_manifest(platform_diff)
    assert status == "rejected" and "platform" in reason

    # fingerprint differs, feature diff is ONLY the XLA tuning
    # pseudo-features -> cosmetic, loads
    cosmetic = dict(
        manifest,
        fingerprint="0" * 12,
        cpu_features=sorted(
            set(manifest["cpu_features"]) ^ {"prefer-no-gather"}
        ),
    )
    assert programs.classify_manifest(cosmetic) == ("cosmetic", "")

    # a real ISA feature differs -> rejected
    real_isa = dict(
        manifest,
        fingerprint="0" * 12,
        cpu_features=sorted(
            set(manifest["cpu_features"]) ^ {"avx512_fake_feature"}
        ),
    )
    status, reason = programs.classify_manifest(real_isa)
    assert status == "rejected" and "ISA" in reason


def test_real_isa_mismatch_rejected_at_load_with_jit_fallback(
    shipped_collection, fresh_batcher, monkeypatch, tmp_path
):
    """The tentpole's safety claim: an artifact fingerprinted on a
    genuinely different host NEVER executes — the loader rejects the
    whole manifest before touching payload bytes, counts it loudly, and
    serving falls back to the jit/prelower path with byte-identical
    responses."""
    # reference responses from the ordinary compile path
    monkeypatch.delenv("GORDO_TPU_LOAD_SHIPPED_PROGRAMS", raising=False)
    warmup.warmup_collection(shipped_collection)
    from gordo_tpu.server.utils import load_model

    X = np.zeros((100, N_TAGS), np.float32)
    expected = np.asarray(
        load_model(shipped_collection, MACHINE_NAME).predict(X)
    )

    # a copy of the artifact stamped with a different host's fingerprint
    tampered = _copy_collection(shipped_collection, tmp_path)
    manifest, path = _manifest(tampered)
    manifest["fingerprint"] = "deadbeef0000"
    manifest["cpu_features"] = sorted(
        set(manifest["cpu_features"]) ^ {"avx512_fake_feature"}
    )
    with open(path, "w") as fh:
        json.dump(manifest, fh)

    monkeypatch.setenv("GORDO_TPU_LOAD_SHIPPED_PROGRAMS", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    from gordo_tpu.server import utils as server_utils

    server_utils.evict_machine(MACHINE_NAME)
    rejected_before = metric_catalog.AOT_PROGRAMS.value(source="rejected")
    report = warmup.warmup_collection(tampered)
    assert report["failed"] == []
    assert report["aot_shipped"] == 0
    assert report["aot_rejected"] == 8
    assert (
        metric_catalog.AOT_PROGRAMS.value(source="rejected")
        - rejected_before
    ) == 8
    batcher = batcher_mod.peek_batcher()
    assert batcher.aot_stats["shipped"] == 0
    # the jit/prelower fallback still produced working, identical output
    actual = np.asarray(load_model(tampered, MACHINE_NAME).predict(X))
    np.testing.assert_array_equal(actual, expected)


def test_cosmetic_feature_diff_still_loads(
    shipped_collection, fresh_batcher, monkeypatch, tmp_path
):
    """The round-4 lesson carried over: a fingerprint diff caused ONLY by
    the XLA tuning pseudo-features (prefer-no-gather/-scatter) cannot
    SIGILL and must not cost cold-start warmth."""
    tampered = _copy_collection(shipped_collection, tmp_path)
    manifest, path = _manifest(tampered)
    manifest["fingerprint"] = "0" * 12  # no longer matches this host
    manifest["cpu_features"] = sorted(
        set(manifest["cpu_features"]) ^ {"prefer-no-gather"}
    )
    with open(path, "w") as fh:
        json.dump(manifest, fh)

    monkeypatch.setenv("GORDO_TPU_LOAD_SHIPPED_PROGRAMS", "1")
    from gordo_tpu.server import utils as server_utils

    server_utils.evict_machine(MACHINE_NAME)
    report = warmup.warmup_collection(tampered)
    assert report["failed"] == []
    assert report["aot_shipped"] == 8
    assert report["aot_rejected"] == 0


# ----------------------------------------------------------- drift hot swap
def test_hotswap_loads_delta_revisions_shipped_programs(
    shipped_collection, fresh_batcher, monkeypatch, tmp_path
):
    """The drift loop's zero-downtime swap pre-warms through the same
    loader: a committed delta revision's shipped programs are installed
    in the batcher's AOT cache by the swap itself."""
    monkeypatch.setenv("GORDO_TPU_LOAD_SHIPPED_PROGRAMS", "1")
    # a serving collection + a committed drift revision beside it, both
    # carrying shipped programs (the rebuild runs with the same env)
    parent = tmp_path / "serve"
    collection = parent / "rev-1"
    shutil.copytree(shipped_collection, collection)
    rev_dir = parent / f"{hotswap.REVISION_PREFIX}0001"
    shutil.copytree(shipped_collection, rev_dir)
    with open(rev_dir / hotswap.COMPLETE_MARKER, "w") as fh:
        json.dump({"machines": [MACHINE_NAME]}, fh)

    hotswap.reset_for_tests()
    from gordo_tpu.server import utils as server_utils

    server_utils.evict_machine(MACHINE_NAME)
    try:
        swapped = hotswap.poll_once(str(collection))
        assert swapped == [MACHINE_NAME]
        batcher = batcher_mod.peek_batcher()
        assert batcher is not None
        # the swap's pre-warm deserialized the revision's programs
        assert batcher.aot_stats["shipped"] >= 8
        assert warmup.last_report()["aot_shipped"] == 8
    finally:
        hotswap.reset_for_tests()
        server_utils.evict_machine(MACHINE_NAME)
