"""
Transformer/TCN model families + attention ops (new capability — the
reference zoo stops at LSTMs, SURVEY.md §5 "long-context: absent").
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gordo_tpu.models import models
from gordo_tpu.models.factories import tcn_model, transformer_model
from gordo_tpu.models.spec import (
    ModelSpec,
    PoolLayer,
    TCNBlock,
    TransformerBlock,
    DenseLayer,
    PositionalEncoding,
)
from gordo_tpu.ops import nn
from gordo_tpu.ops.attention import (
    dot_product_attention_xla,
    multihead_attention,
)
from gordo_tpu.ops.pallas_kernels import flash_attention
from gordo_tpu.serializer import from_definition, into_definition


# ---------------------------------------------------------------- attention
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(causal):
    rng = np.random.RandomState(0)
    q, k, v = (
        jnp.asarray(rng.randn(2, 256, 8).astype(np.float32)) for _ in range(3)
    )
    ref = dot_product_attention_xla(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_attention_grad_matches_reference():
    rng = np.random.RandomState(1)
    q, k, v = (
        jnp.asarray(rng.randn(1, 128, 8).astype(np.float32)) for _ in range(3)
    )

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention_xla(q, k, v, causal=True) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_multihead_attention_shapes_and_heads():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(3, 16, 32).astype(np.float32))
    out = multihead_attention(x, x, x, num_heads=4)
    assert out.shape == (3, 16, 32)
    with pytest.raises(ValueError):
        multihead_attention(x, x, x, num_heads=5)


# ------------------------------------------------------------------ factories
def test_transformer_factory_spec():
    spec = transformer_model(
        n_features=6, lookback_window=32, d_model=16, num_heads=2, num_blocks=3
    )
    assert isinstance(spec, ModelSpec)
    assert spec.lookback_window == 32
    blocks = [l for l in spec.layers if isinstance(l, TransformerBlock)]
    assert len(blocks) == 3
    assert all(b.d_model == 16 and b.num_heads == 2 for b in blocks)
    assert isinstance(spec.layers[0], DenseLayer) and spec.layers[0].units == 16
    assert isinstance(spec.layers[1], PositionalEncoding)
    assert isinstance(spec.layers[-2], PoolLayer)
    assert spec.layers[-1].units == 6
    # frozen + hashable → usable as a jit static arg / bucket key
    assert hash(spec) == hash(
        transformer_model(
            n_features=6, lookback_window=32, d_model=16, num_heads=2, num_blocks=3
        )
    )


def test_tcn_factory_spec_dilations():
    spec = tcn_model(n_features=4, lookback_window=16, filters=8, num_blocks=3)
    blocks = [l for l in spec.layers if isinstance(l, TCNBlock)]
    assert [b.dilation for b in blocks] == [1, 2, 4]


def test_factories_reject_degenerate_configs():
    with pytest.raises(ValueError):
        tcn_model(n_features=4, num_blocks=0)
    with pytest.raises(ValueError):
        tcn_model(n_features=4, dilations=())
    with pytest.raises(ValueError):
        transformer_model(n_features=4, lookback_window=1)
    with pytest.raises(ValueError):
        models.TransformerAutoEncoder(kind="transformer_model", lookback_window=1)


def test_sequence_estimators_default_lookback_window():
    model = models.TCNAutoEncoder(kind="tcn_model")
    assert model.lookback_window == 144


def test_ops_attention_importable_standalone():
    """gordo_tpu.ops.attention as a process's first gordo_tpu import must not
    trip the ops ↔ models import cycle."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, "-c", "import gordo_tpu.ops.attention; print('ok')"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "ok" in proc.stdout


# ---------------------------------------------------------------- causality
def _sequence_output(layers, n_features, x):
    spec = ModelSpec(
        layers=layers, n_features=n_features, n_features_out=n_features
    )
    params = nn.init_model_params(jax.random.PRNGKey(0), spec)
    out, _ = nn.apply_model(spec, params, jnp.asarray(x))
    return np.asarray(out)


@pytest.mark.parametrize(
    "layers",
    [
        (TCNBlock(filters=8, kernel_size=3, dilation=2),),
        (
            DenseLayer(units=8),
            TransformerBlock(d_model=8, num_heads=2, ff_dim=16, causal=True),
        ),
    ],
    ids=["tcn", "transformer-causal"],
)
def test_causal_layers_ignore_future(layers):
    rng = np.random.RandomState(3)
    x = rng.randn(1, 12, 4).astype(np.float32)
    out_a = _sequence_output(layers, 4, x)
    x_perturbed = x.copy()
    x_perturbed[:, 8:, :] += 10.0  # change only the future
    out_b = _sequence_output(layers, 4, x_perturbed)
    np.testing.assert_allclose(out_a[:, :8], out_b[:, :8], atol=1e-5)
    assert not np.allclose(out_a[:, 8:], out_b[:, 8:])


# --------------------------------------------------------------- estimators
@pytest.mark.parametrize(
    "cls,kind,lookahead",
    [
        (models.TransformerAutoEncoder, "transformer_model", 0),
        (models.TransformerForecast, "transformer_model", 1),
        (models.TCNAutoEncoder, "tcn_model", 0),
        (models.TCNForecast, "tcn_model", 1),
    ],
)
def test_estimator_fit_predict_window_semantics(cls, kind, lookahead):
    rng = np.random.RandomState(4)
    X = rng.rand(40, 3).astype(np.float32)
    model = cls(
        kind=kind,
        lookback_window=8,
        batch_size=16,
        epochs=1,
        d_model=8,
        num_heads=2,
        ff_dim=16,
        num_blocks=1,
        filters=8,
    )
    model.fit(X, X)
    out = model.predict(X)
    assert out.shape == (40 - 8 + 1 - lookahead, 3)
    assert np.all(np.isfinite(out))
    assert isinstance(model.score(X, X), float)


def test_transformer_training_reduces_loss():
    rng = np.random.RandomState(5)
    t = np.linspace(0, 20 * np.pi, 300)
    X = np.stack([np.sin(t), np.cos(t)], axis=1).astype(np.float32)
    model = models.TransformerAutoEncoder(
        kind="transformer_model",
        lookback_window=16,
        batch_size=32,
        epochs=15,
        d_model=16,
        num_heads=2,
        ff_dim=32,
        num_blocks=1,
    )
    model.fit(X, X)
    losses = model.history["loss"]
    assert losses[-1] < losses[0] * 0.7


# -------------------------------------------------------------- serializer
def test_transformer_round_trips_through_definition():
    definition = {
        "gordo_tpu.models.models.TransformerAutoEncoder": {
            "kind": "transformer_model",
            "lookback_window": 12,
            "d_model": 8,
            "num_heads": 2,
            "epochs": 1,
        }
    }
    model = from_definition(definition)
    assert isinstance(model, models.TransformerAutoEncoder)
    assert model.lookback_window == 12
    round_tripped = into_definition(model)
    assert from_definition(round_tripped).get_params() == model.get_params()


def test_pickle_fitted_tcn():
    import pickle

    rng = np.random.RandomState(6)
    X = rng.rand(30, 2).astype(np.float32)
    model = models.TCNAutoEncoder(
        kind="tcn_model", lookback_window=4, epochs=1, filters=4, num_blocks=2
    )
    model.fit(X, X)
    clone = pickle.loads(pickle.dumps(model))
    np.testing.assert_allclose(clone.predict(X), model.predict(X), atol=1e-6)


def test_flash_attention_rejects_cross_length_kv():
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(1, 128, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 192, 8).astype(np.float32))
    with pytest.raises(ValueError, match="equal Q/K/V sequence lengths"):
        flash_attention(q, k, k, interpret=True)


def test_ring_impl_matches_xla_from_config():
    """attention=ring on the Transformer factory routes through the
    sequence-parallel ring and matches the xla path numerically."""
    from gordo_tpu.ops.attention import dot_product_attention

    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(2, 4, 64, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 4, 64, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 4, 64, 8).astype(np.float32))
    ring = dot_product_attention(q, k, v, causal=True, impl="ring")
    xla = dot_product_attention(q, k, v, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(ring), np.asarray(xla), atol=1e-5)

    # and end-to-end from a model definition
    spec = transformer_model(
        4, lookback_window=64, d_model=16, num_heads=2, num_blocks=1,
        attention="ring",
    )
    assert all(
        blk.attention_impl == "ring"
        for blk in spec.layers
        if hasattr(blk, "attention_impl")
    )
    model = models.TransformerAutoEncoder(
        kind="transformer_model", lookback_window=64, d_model=16, num_heads=2,
        ff_dim=32, num_blocks=1, attention="ring", epochs=1, batch_size=8,
    )
    X = np.random.RandomState(3).rand(80, 4).astype(np.float32)
    model.fit(X, X)
    assert np.all(np.isfinite(model.predict(X)))


def test_ring_machines_take_serial_path():
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel.batch_trainer import _plan_machine

    cfg = {
        "name": "ring-m",
        "dataset": {
            "type": "RandomDataset",
            "tags": ["r-0", "r-1", "r-2", "r-3"],
            "train_start_date": "2019-01-01T00:00:00+00:00",
            "train_end_date": "2019-01-03T00:00:00+00:00",
        },
        "model": {
            "gordo_tpu.models.models.TransformerAutoEncoder": {
                "kind": "transformer_model",
                "lookback_window": 64,
                "attention": "ring",
            }
        },
    }
    assert _plan_machine(Machine.from_config(cfg, project_name="t")) is None
    cfg["model"]["gordo_tpu.models.models.TransformerAutoEncoder"]["attention"] = "auto"
    assert _plan_machine(Machine.from_config(cfg, project_name="t")) is not None


def test_fused_qkv_matches_unfused_and_tp_disables_it():
    """The fused (d, 3d) QKV projection is bit-equivalent math to the three
    separate matmuls, and prepare_tp_spec turns it off — the concat of
    column-sharded weights would break the Megatron comm pattern."""
    import dataclasses

    spec = transformer_model(n_features=4, lookback_window=16)
    params = nn.init_model_params(jax.random.PRNGKey(0), spec)
    x = jnp.asarray(np.random.RandomState(0).rand(3, 16, 4), jnp.float32)
    out_fused, _ = nn.apply_model(spec, params, x)

    unfused_layers = tuple(
        dataclasses.replace(l, fuse_qkv=False)
        if isinstance(l, TransformerBlock) else l
        for l in spec.layers
    )
    spec_unfused = dataclasses.replace(spec, layers=unfused_layers)
    out_unfused, _ = nn.apply_model(spec_unfused, params, x)
    np.testing.assert_allclose(
        np.asarray(out_fused), np.asarray(out_unfused), rtol=1e-6, atol=1e-6
    )

    # TP pins fusion off on every block (and a pre-field pickle defaults on)
    from gordo_tpu.parallel.tensor_parallel import prepare_tp_spec

    tp_spec = prepare_tp_spec(
        dataclasses.replace(
            transformer_model(n_features=4, lookback_window=16, num_heads=4),
            tensor_parallel=4,
        )
    )
    blocks = [l for l in tp_spec.layers if isinstance(l, TransformerBlock)]
    assert blocks and all(not b.fuse_qkv for b in blocks)


def test_causal_conv_matmul_form_matches_conv_general_dilated():
    """The TCN causal conv is implemented as k shifted matmuls (XLA CPU has
    no fast dilated-conv path — measured ~32x slower — and matmuls are the
    MXU's native op). Pin it against lax.conv_general_dilated."""
    rng = np.random.RandomState(0)
    for dilation in (1, 2, 4, 8):
        x = jnp.asarray(rng.rand(3, 50, 5).astype(np.float32))
        w = jnp.asarray(rng.rand(3, 5, 7).astype(np.float32))
        ref = jax.lax.conv_general_dilated(
            x, w, window_strides=(1,),
            padding=[((w.shape[0] - 1) * dilation, 0)],
            rhs_dilation=(dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        got = nn._causal_conv1d(x, w, dilation)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_causal_conv1d_matches_lax_conv_and_short_windows():
    """The no-pad post-shift causal conv (one clean GEMM + fused shifted
    adds; round-5 CPU fast-path rework) must match XLA's own dilated conv
    bit-for-bit in f32, including sequences SHORTER than the receptive
    field (taps whose whole output precedes the series start contribute
    zero)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gordo_tpu.ops.nn import _causal_conv1d

    rng = np.random.RandomState(7)
    for t, dilation in [(144, 1), (144, 8), (16, 8), (3, 2), (1, 4)]:
        x = jnp.asarray(rng.standard_normal((2, t, 5)), jnp.float32)
        kernel = jnp.asarray(rng.standard_normal((3, 5, 4)), jnp.float32)
        got = _causal_conv1d(x, kernel, dilation)
        ref = jax.lax.conv_general_dilated(
            x,
            kernel,
            window_strides=(1,),
            padding=[((kernel.shape[0] - 1) * dilation, 0)],
            rhs_dilation=(dilation,),
            dimension_numbers=("NWC", "WIO", "NWC"),
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5,
            err_msg=f"t={t} dilation={dilation}",
        )


def test_flash_attention_lowers_through_mosaic_for_tpu():
    """The interpret-mode tests above prove the kernel's MATH; this proves
    its TILING. jax.export with platforms=["tpu"] runs the real Mosaic
    lowering on a CPU host — which round 5 found rejecting the kernel
    outright (the flat (1, block_q) lse output block violates the (8, 128)
    tile rule; lse/delta are now lane-replicated). Any future block-spec
    edit that breaks TPU lowering fails here, in CI, without a TPU."""
    import jax
    import jax.numpy as jnp
    from jax import export

    q = jnp.zeros((2, 4, 512, 64), jnp.float32)

    def fwd(q, k, v):
        return flash_attention(q, k, v, causal=True, interpret=False)

    def grads(q, k, v):
        return jax.grad(
            lambda a, b, c: jnp.sum(fwd(a, b, c) ** 2), argnums=(0, 1, 2)
        )(q, k, v)

    fwd_mlir = export.export(jax.jit(fwd), platforms=["tpu"])(
        q, q, q
    ).mlir_module()
    assert fwd_mlir.count("tpu_custom_call") == 1
    bwd_mlir = export.export(jax.jit(grads), platforms=["tpu"])(
        q, q, q
    ).mlir_module()
    # fwd kernel + dq kernel + fused dk/dv kernel
    assert bwd_mlir.count("tpu_custom_call") == 3

    # bfloat16 — the windowed fleets' TPU compute dtype — has DIFFERENT
    # minimum tiles ((16, 128) vs f32's (8, 128)), so its lowering is a
    # separate thing to prove
    qb = jnp.zeros((2, 4, 512, 64), jnp.bfloat16)
    bf16_mlir = export.export(jax.jit(grads), platforms=["tpu"])(
        qb, qb, qb
    ).mlir_module()
    assert bf16_mlir.count("tpu_custom_call") == 3


def test_flash_dispatch_gate_matches_lowering_support(monkeypatch):
    """_flash_ok must only admit shapes the Mosaic lowering handles: dh<64
    was measured to hang TPU lowering, and t>4096 approaches the VMEM
    budget (long sequences are ring attention's job)."""
    import jax.numpy as jnp

    from gordo_tpu.ops import attention

    monkeypatch.setattr(
        attention.jax, "default_backend", lambda: "tpu"
    )

    def ok(t, dh):
        x = jnp.zeros((1, 2, t, dh), jnp.float32)
        return attention._flash_ok(x, x)

    assert ok(512, 64) and ok(4096, 128)
    assert not ok(512, 8)      # sub-64 head dim: lowering hang
    assert not ok(512, 16)
    assert not ok(8192, 64)    # past the VMEM-budget cap
    assert not ok(128, 64)     # below the win threshold


def test_flash_attention_bfloat16_matches_reference():
    """bf16 inputs with f32 accumulators: within bf16 tolerance of the XLA
    reference (the windowed fleets' TPU compute dtype)."""
    rng = np.random.RandomState(3)
    q, k, v = (
        jnp.asarray(rng.standard_normal((2, 2, 256, 32)), jnp.float32).astype(
            jnp.bfloat16
        )
        for _ in range(3)
    )
    raw = flash_attention(q, k, v, causal=True, interpret=True)
    # output stays at the input dtype; accumulation is f32 inside
    assert raw.dtype == jnp.bfloat16
    ref = dot_product_attention_xla(q, k, v, causal=True).astype(jnp.float32)
    got = raw.astype(jnp.float32)
    assert ref.shape == got.shape
    rel = float(
        jnp.max(jnp.abs(ref - got)) / (jnp.max(jnp.abs(ref)) + 1e-9)
    )
    assert rel < 2e-2, rel
