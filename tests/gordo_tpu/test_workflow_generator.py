"""
Workflow-generator tests.

Mirrors the reference's strategy (SURVEY.md §4): render the template through
the CLI and assert on the PARSED YAML structure — no cluster required
(reference tests/gordo/workflow/test_workflow_generator/
test_workflow_generator.py:37-77).
"""

import json

import pytest
import yaml
from click.testing import CliRunner

from gordo_tpu.cli.cli import gordo
from gordo_tpu.cli.workflow_generator import generate_workflow_docs
from gordo_tpu.workflow.workflow_generator import (
    TimestampNotTZAware,
    chunk_machines,
    default_image_pull_policy,
    get_dict_from_yaml,
    sanitize_docker_tag,
    validate_generate_owner_ref,
)


def _config_yaml(n_machines=3) -> str:
    machines = []
    for i in range(n_machines):
        machines.append(
            {
                "name": f"machine-{i}",
                "dataset": {
                    "type": "RandomDataset",
                    "tags": [f"tag-{i}-{j}" for j in range(4)],
                    "train_start_date": "2019-01-01T00:00:00+00:00",
                    "train_end_date": "2019-01-08T00:00:00+00:00",
                },
                "model": {
                    "gordo_tpu.models.models.AutoEncoder": {
                        "kind": "feedforward_hourglass"
                    }
                },
            }
        )
    return yaml.safe_dump({"machines": machines})


@pytest.fixture
def config_file(tmp_path):
    p = tmp_path / "config.yml"
    p.write_text(_config_yaml())
    return str(p)


def _render(config_file, **overrides) -> list:
    overrides.setdefault("client_start_date", "2019-01-01T00:00:00Z")
    overrides.setdefault("client_end_date", "2019-01-02T00:00:00Z")
    content = generate_workflow_docs(
        machine_config=config_file, project_name="test-proj", **overrides
    )
    return [d for d in yaml.safe_load_all(content) if d]


def test_generate_renders_valid_workflow_yaml(config_file):
    docs = _render(config_file)
    assert len(docs) == 1
    wf = docs[0]
    assert wf["kind"] == "Workflow"
    assert wf["metadata"]["generateName"] == "gordo-tpu-test-proj-"
    labels = wf["metadata"]["labels"]
    assert labels["applications.gordo.equinor.com/project-name"] == "test-proj"
    template_names = {t["name"] for t in wf["spec"]["templates"]}
    assert {
        "ensure-single-workflow",
        "tpu-batch-builder",
        "gordo-server-deployment",
        "gordo-client",
        "workflow-cleanup",
        "do-all",
    } <= template_names


def test_generate_batches_machines_into_chunks(config_file):
    wf = _render(config_file, machines_per_tpu_worker=2)[0]
    dag = next(
        t for t in wf["spec"]["templates"] if t["name"] == "do-all"
    )["dag"]
    builder_tasks = [
        t for t in dag["tasks"] if t["name"].startswith("tpu-batch-builder-")
    ]
    # 3 machines, 2 per chunk => 2 chunks (not 3 per-machine pods)
    assert len(builder_tasks) == 2
    # chunk tasks carry only machine names (full config is staged onto the
    # PVC by stage-config, keeping parameters tiny)
    names_param = builder_tasks[0]["arguments"]["parameters"][1]
    assert names_param["name"] == "machine-names"
    assert names_param["value"] == "machine-0,machine-1"
    assert "stage-config" in builder_tasks[0]["dependencies"]


def _staged_config(wf: dict) -> dict:
    """Extract the YAML embedded in the stage-config heredoc."""
    stage = next(
        t for t in wf["spec"]["templates"] if t["name"] == "stage-config"
    )
    source = stage["script"]["source"]
    start = source.index("\n", source.index("GORDO_TPU_CONFIG_EOF")) + 1
    end = source.rindex("GORDO_TPU_CONFIG_EOF")
    return yaml.safe_load(source[start:end])


def test_generate_stage_config_contains_full_machines(config_file):
    wf = _render(config_file)[0]
    # the heredoc embeds the full group config incl. model definitions
    staged = _staged_config(wf)
    assert len(staged["machines"]) == 3
    assert "model" in staged["machines"][0]
    assert staged["machines"][0]["name"] == "machine-0"


def test_generate_client_tasks_depend_on_chunk(config_file):
    wf = _render(config_file, machines_per_tpu_worker=2)[0]
    dag = next(
        t for t in wf["spec"]["templates"] if t["name"] == "do-all"
    )["dag"]
    tasks = {t["name"]: t for t in dag["tasks"]}
    assert "client-machine-2" in tasks
    deps = tasks["client-wait-machine-2"]["dependencies"]
    assert "tpu-batch-builder-g0c1" in deps


def test_generate_split_workflows(tmp_path):
    p = tmp_path / "big.yml"
    p.write_text(_config_yaml(n_machines=7))
    docs = _render(str(p), split_workflows=3)
    assert len(docs) == 3  # 3 + 3 + 1 machines


def test_generate_keda_autoscaler(config_file):
    wf = _render(config_file, ml_server_hpa_type="keda")[0]
    scaler = next(
        t
        for t in wf["spec"]["templates"]
        if t["name"] == "gordo-server-autoscaler"
    )
    manifest = yaml.safe_load(scaler["resource"]["manifest"])
    assert manifest["kind"] == "ScaledObject"
    assert manifest["spec"]["triggers"][0]["type"] == "prometheus"


def test_generate_hpa_default_max_replicas(config_file):
    wf = _render(config_file)[0]
    scaler = next(
        t
        for t in wf["spec"]["templates"]
        if t["name"] == "gordo-server-autoscaler"
    )
    manifest = yaml.safe_load(scaler["resource"]["manifest"])
    assert manifest["kind"] == "HorizontalPodAutoscaler"
    assert manifest["spec"]["maxReplicas"] == 30  # 10 x 3 machines


def test_generate_custom_builder_envs(config_file):
    envs = json.dumps([{"name": "FOO", "value": "bar"}])
    wf = _render(config_file, custom_model_builder_envs=envs)[0]
    builder = next(
        t for t in wf["spec"]["templates"] if t["name"] == "tpu-batch-builder"
    )
    env_names = [e["name"] for e in builder["container"]["env"]]
    assert "FOO" in env_names


def test_generate_postgres_reporter_injection(config_file):
    wf = _render(config_file, postgres_host="pg.example.com")[0]
    staged = _staged_config(wf)
    reporters = staged["machines"][0]["runtime"]["reporters"]
    assert any("PostgresReporter" in str(r) for r in reporters)


def test_generate_custom_env_valuefrom(config_file):
    envs = json.dumps(
        [
            {
                "name": "POD_IP",
                "valueFrom": {"fieldRef": {"fieldPath": "status.podIP"}},
            }
        ]
    )
    wf = _render(config_file, custom_model_builder_envs=envs)[0]
    builder = next(
        t for t in wf["spec"]["templates"] if t["name"] == "tpu-batch-builder"
    )
    pod_ip = next(
        e for e in builder["container"]["env"] if e["name"] == "POD_IP"
    )
    assert pod_ip["valueFrom"]["fieldRef"]["fieldPath"] == "status.podIP"


def test_generate_via_cli(config_file, tmp_path):
    out = tmp_path / "wf.yml"
    runner = CliRunner()
    result = runner.invoke(
        gordo,
        [
            "workflow",
            "generate",
            "--machine-config",
            config_file,
            "--project-name",
            "cli-proj",
            "--client-start-date",
            "2019-01-01T00:00:00Z",
            "--client-end-date",
            "2019-01-02T00:00:00Z",
            "--output-file",
            str(out),
        ],
    )
    assert result.exit_code == 0, result.output
    docs = list(yaml.safe_load_all(out.read_text()))
    assert docs[0]["kind"] == "Workflow"


def test_owner_references_validation():
    with pytest.raises(TypeError):
        validate_generate_owner_ref([{"name": "x"}])
    good = [
        {"uid": "1", "name": "x", "kind": "Deployment", "apiVersion": "v1"}
    ]
    assert validate_generate_owner_ref(good) == good


def test_tz_naive_timestamp_rejected(tmp_path):
    p = tmp_path / "bad.yml"
    p.write_text("machines: []\nstart: 2019-01-01 00:00:00\n")
    with pytest.raises(TimestampNotTZAware):
        get_dict_from_yaml(str(p))


def test_gordo_crd_unwrap():
    doc = yaml.safe_dump(
        {"kind": "Gordo", "spec": {"config": {"machines": []}}}
    )
    assert get_dict_from_yaml(doc) == {"machines": []}


def test_image_pull_policy_and_tag():
    assert default_image_pull_policy("latest") == "Always"
    assert default_image_pull_policy("1.2.3") == "IfNotPresent"
    assert default_image_pull_policy("pr-12") == "Always"
    assert sanitize_docker_tag("feature/x y") == "feature-x-y"


def test_chunk_machines():
    assert chunk_machines(list(range(5)), 2) == [[0, 1], [2, 3], [4]]
    assert chunk_machines([], 3) == []
    with pytest.raises(ValueError):
        chunk_machines([1], 0)


def test_multihost_slice_rendering():
    """--tpu-workers-per-slice > 1 must render per-chunk coordinator
    Services and one rank-parameterized builder pod per slice host."""
    docs = generate_workflow_docs(
        _config_yaml(4), project_name="mh-proj", tpu_workers_per_slice=2,
        client_start_date="2019-01-01T00:00:00Z",
        client_end_date="2019-01-02T00:00:00Z",
    )
    parsed = [d for d in yaml.safe_load_all(docs) if d]
    templates = {t["name"]: t for d in parsed for t in d["spec"]["templates"]}
    assert "gordo-coordinator-service" in templates
    svc = yaml.safe_load(
        templates["gordo-coordinator-service"]["resource"]["manifest"]
    )
    assert svc["spec"]["clusterIP"] == "None"  # k8s headless literal
    assert svc["spec"]["selector"]["gordo-tpu/worker"] == "0"

    builder = templates["tpu-batch-builder"]
    env = {
        e["name"]: e.get("value")
        for e in builder["container"]["env"]
    }
    assert env["GORDO_TPU_NUM_PROCESSES"] == "2"
    assert env["GORDO_TPU_PROCESS_ID"] == "{{inputs.parameters.worker-id}}"
    # the coordinator address is a runtime parameter; the DAG passes the
    # generator-computed (revision-scoped, 63-char-bounded) name
    assert env["GORDO_TPU_COORDINATOR_ADDRESS"] == (
        "{{inputs.parameters.coord-name}}:8476"
    )

    dag = templates["do-all"]["dag"]["tasks"]
    builders = [t for t in dag if t["template"] == "tpu-batch-builder"]
    assert builders and all("withSequence" in t for t in builders)
    assert all(
        t["withSequence"]["count"] == "2" for t in builders
    )
    coords = [t for t in dag if t["template"] == "gordo-coordinator-service"]
    assert len(coords) == len(builders)
    for task in builders + coords:
        params = {
            p["name"]: p["value"] for p in task["arguments"]["parameters"]
        }
        assert params["coord-name"].startswith("gordo-coord-mh-proj-r1-")
        assert len(params["coord-name"]) <= 63
        assert params["chunk-label"].startswith("mh-proj-r1-")
        assert len(params["chunk-label"]) <= 63


def test_singlehost_has_no_coordinator():
    docs = generate_workflow_docs(
        _config_yaml(2), project_name="sh-proj",
        client_start_date="2019-01-01T00:00:00Z",
        client_end_date="2019-01-02T00:00:00Z",
    )
    parsed = [d for d in yaml.safe_load_all(docs) if d]
    names = [t["name"] for d in parsed for t in d["spec"]["templates"]]
    assert "gordo-coordinator-service" not in names
    assert "withSequence" not in docs


def test_side_deployments_rendered_and_gated(config_file):
    docs = _render(config_file)
    tmpl_names = {t["name"] for t in docs[0]["spec"]["templates"]}
    assert {
        "gordo-influx", "gordo-influx-service",
        "gordo-postgres", "gordo-postgres-service",
        "gordo-grafana", "gordo-grafana-service",
    } <= tmpl_names
    dag = next(t for t in docs[0]["spec"]["templates"] if t["name"] == "do-all")
    task_names = {t["name"] for t in dag["dag"]["tasks"]}
    assert {"deploy-influx", "deploy-postgres", "deploy-grafana"} <= task_names
    # manifests must themselves be valid k8s YAML
    for t in docs[0]["spec"]["templates"]:
        if "resource" in t and t["name"].startswith(
            ("gordo-influx", "gordo-postgres", "gordo-grafana")
        ):
            manifest = yaml.safe_load(t["resource"]["manifest"])
            assert manifest["kind"] in ("StatefulSet", "Deployment", "Service")

    # in-cluster postgres becomes every machine's reporter sink
    builder_tmpl = next(
        t for t in docs[0]["spec"]["templates"] if t["name"] == "stage-config"
    )
    staged = builder_tmpl["script"]["source"]
    assert "gordo-postgres-test-proj" in staged

    # gates
    off = _render(
        config_file,
        enable_influx=False,
        enable_postgres=False,
        enable_grafana=False,
    )
    off_names = {t["name"] for t in off[0]["spec"]["templates"]}
    assert not any(n.startswith(("gordo-influx", "gordo-postgres", "gordo-grafana"))
                   for n in off_names)

    # an external postgres host suppresses the in-cluster deploy but keeps
    # the reporter pointed at the external host
    ext = _render(config_file, postgres_host="pg.example.com")
    ext_names = {t["name"] for t in ext[0]["spec"]["templates"]}
    assert "gordo-postgres" not in ext_names
    staged_ext = next(
        t for t in ext[0]["spec"]["templates"] if t["name"] == "stage-config"
    )["script"]["source"]
    assert "pg.example.com" in staged_ext


def test_workflow_validator_catches_broken_docs(config_file):
    from gordo_tpu.workflow.validate import (
        WorkflowValidationError,
        validate_workflow_docs,
    )

    content = generate_workflow_docs(
        machine_config=config_file, project_name="test-proj",
        client_start_date="2019-01-01T00:00:00Z",
        client_end_date="2019-01-02T00:00:00Z",
    )
    validate_workflow_docs(content)  # rendered docs are valid

    doc = yaml.safe_load(content.split("\n---\n")[0])

    # undefined template reference in the DAG
    bad = yaml.safe_load(content.split("\n---\n")[0])
    dag = next(t for t in bad["spec"]["templates"] if "dag" in t)
    dag["dag"]["tasks"][0]["template"] = "no-such-template"
    with pytest.raises(WorkflowValidationError, match="undefined template"):
        validate_workflow_docs(yaml.safe_dump(bad))

    # dependency cycle
    bad = yaml.safe_load(content.split("\n---\n")[0])
    dag = next(t for t in bad["spec"]["templates"] if "dag" in t)
    t0, t1 = dag["dag"]["tasks"][0], dag["dag"]["tasks"][1]
    t0["dependencies"] = [t1["name"]]
    t1["dependencies"] = [t0["name"]]
    with pytest.raises(WorkflowValidationError, match="cycle"):
        validate_workflow_docs(yaml.safe_dump(bad))

    # invalid DNS-1123 template name
    bad = yaml.safe_load(content.split("\n---\n")[0])
    bad["spec"]["templates"][0]["name"] = "Not_A_Valid_Name"
    with pytest.raises(WorkflowValidationError, match="DNS-1123"):
        validate_workflow_docs(yaml.safe_dump(bad))

    # unquoted numeric env value
    bad = yaml.safe_load(content.split("\n---\n")[0])
    for t in bad["spec"]["templates"]:
        if "container" in t and t["container"].get("env"):
            t["container"]["env"][0]["value"] = 42
            break
    with pytest.raises(WorkflowValidationError, match="must be a string"):
        validate_workflow_docs(yaml.safe_dump(bad))

    # missing entrypoint
    bad = yaml.safe_load(content.split("\n---\n")[0])
    del bad["spec"]["entrypoint"]
    with pytest.raises(WorkflowValidationError, match="entrypoint"):
        validate_workflow_docs(yaml.safe_dump(bad))


def test_clients_require_dates():
    """Enabled clients with empty dates would render `predict "" ""` tasks
    that all fail in Argo — generation must fail with the actionable knob
    instead, and --disable-clients must lift the requirement."""
    import click

    with pytest.raises(click.ClickException, match="client-start-date"):
        generate_workflow_docs(_config_yaml(2), project_name="d-proj")
    # malformed or tz-naive dates fail at the same gate, not in every
    # rendered client task's Argo retry loop
    with pytest.raises(click.ClickException, match="ISO-8601"):
        generate_workflow_docs(
            _config_yaml(2), project_name="d-proj",
            client_start_date="banana",
            client_end_date="2019-01-02T00:00:00Z",
        )
    with pytest.raises(click.ClickException, match="timezone"):
        generate_workflow_docs(
            _config_yaml(2), project_name="d-proj",
            client_start_date="2019-01-01T00:00:00",
            client_end_date="2019-01-02T00:00:00Z",
        )
    docs = generate_workflow_docs(
        _config_yaml(2), project_name="d-proj", enable_clients=False
    )
    parsed = [d for d in yaml.safe_load_all(docs) if d]
    dag_tasks = [
        t["name"]
        for d in parsed
        for tpl in d["spec"]["templates"]
        for t in (tpl.get("dag", {}) or {}).get("tasks", [])
    ]
    assert not any(name.startswith("client-") for name in dag_tasks)


def test_hpa_max_replicas_scales_with_project_not_group():
    """The server HPA is ONE shared per-project resource; its default
    ceiling must come from the project's machine count, not whichever
    split-workflow group's doc happens to apply last."""
    docs = generate_workflow_docs(
        _config_yaml(35), project_name="hpa-proj", split_workflows=30,
        client_start_date="2019-01-01T00:00:00Z",
        client_end_date="2019-01-02T00:00:00Z",
    )
    parsed = [d for d in yaml.safe_load_all(docs) if d]
    assert len(parsed) == 2  # 30 + 5
    ceilings = {_max_replicas_of(d) for d in parsed}
    assert ceilings == {350}, ceilings


def _max_replicas_of(doc) -> int:
    """The rendered HPA/ScaledObject ceiling inside one Workflow doc (the
    HPA manifest is an embedded string, so regex the serialized doc)."""
    import re

    hits = re.findall(r"maxReplicas?(?:Count)?\D{0,4}?(\d+)", str(doc))
    assert hits, "no maxReplicas in doc"
    assert len(set(hits)) == 1, hits
    return int(hits[0])


def test_bare_date_rejected_as_tz_naive(tmp_path):
    """Unquoted `2019-01-01` constructs a datetime.date — inherently
    tz-naive; it must hit the same guard as naive datetimes instead of
    slipping through into tz-aware comparisons downstream."""
    from gordo_tpu.workflow.workflow_generator import (
        TimestampNotTZAware,
        get_dict_from_yaml,
    )

    cfg = tmp_path / "c.yaml"
    cfg.write_text("machines:\n  - name: m\n    start: 2019-01-01\n")
    with pytest.raises(TimestampNotTZAware, match="bare date"):
        get_dict_from_yaml(str(cfg))


def test_validator_checks_steps_template_references():
    from gordo_tpu.workflow.validate import validate_workflow_doc

    doc = {
        "apiVersion": "argoproj.io/v1alpha1",
        "kind": "Workflow",
        "metadata": {"name": "w"},
        "spec": {
            "entrypoint": "main",
            "templates": [
                {
                    "name": "main",
                    "steps": [[{"name": "s1", "template": "missing"}]],
                },
            ],
        },
    }
    errors = validate_workflow_doc(doc)
    assert any("undefined template 'missing'" in e for e in errors)


def test_validator_steps_edge_cases():
    """Non-dict step entries report errors (not AttributeError); Argo 3.2+
    inline steps count as a valid template ref."""
    from gordo_tpu.workflow.validate import validate_workflow_doc

    base = {
        "apiVersion": "argoproj.io/v1alpha1",
        "kind": "Workflow",
        "metadata": {"name": "w"},
    }
    malformed = {
        **base,
        "spec": {
            "entrypoint": "main",
            "templates": [{"name": "main", "steps": [["oops"]]}],
        },
    }
    errors = validate_workflow_doc(malformed)
    assert any("must be a mapping" in e for e in errors)

    inline = {
        **base,
        "spec": {
            "entrypoint": "main",
            "templates": [
                {
                    "name": "main",
                    "steps": [[{
                        "name": "s",
                        "inline": {"container": {"image": "i", "command": ["x"]}},
                    }]],
                }
            ],
        },
    }
    assert not any(
        "no template ref" in e for e in validate_workflow_doc(inline)
    )


def test_long_project_names_bound_coordinator_names():
    """A long project name must not push the per-chunk coordinator Service
    name or pod label value past the k8s 63-char cap — the generator
    truncates with a uniqueness hash. (Very long projects are bounded
    earlier by the machine-host validator; 40 chars passes it and brings
    the 'gordo-coord-' + revision + chunk-id concatenation to the edge.)"""
    from gordo_tpu.cli.workflow_generator import _bounded_k8s_name

    base = "gordo-coord-" + "a" * 60 + "-r1-g0c0"
    bounded = _bounded_k8s_name(base)
    assert len(bounded) <= 63
    assert bounded != _bounded_k8s_name(base + "1")  # uniqueness preserved
    assert _bounded_k8s_name("short") == "short"

    long_name = "a" * 40
    docs = generate_workflow_docs(
        _config_yaml(2), project_name=long_name, tpu_workers_per_slice=2,
        client_start_date="2019-01-01T00:00:00Z",
        client_end_date="2019-01-02T00:00:00Z",
    )
    parsed = [d for d in yaml.safe_load_all(docs) if d]
    dag = [
        t for d in parsed for tpl in d["spec"]["templates"]
        if tpl["name"] == "do-all" for t in tpl["dag"]["tasks"]
    ]
    seen = set()
    for task in dag:
        if task["template"] not in ("tpu-batch-builder", "gordo-coordinator-service"):
            continue
        params = {
            p["name"]: p["value"] for p in task["arguments"]["parameters"]
        }
        assert len(params["coord-name"]) <= 63, params["coord-name"]
        assert len(params["chunk-label"]) <= 63
        seen.add(params["coord-name"])
    assert seen  # bounded names stay unique per chunk


def test_server_rollout_gated_on_full_project_readiness():
    """Zero-downtime rollover: every split-workflow doc deploys the same
    server manifest — EXPECTED_MODELS lists the WHOLE project's machines,
    the readiness probe hits /readiness, and maxUnavailable: 0 keeps the
    previous revision serving until the new build completes."""
    docs = generate_workflow_docs(
        _config_yaml(35), project_name="ro-proj", split_workflows=30,
        client_start_date="2019-01-01T00:00:00Z",
        client_end_date="2019-01-02T00:00:00Z",
    )
    parsed = [d for d in yaml.safe_load_all(docs) if d]
    assert len(parsed) == 2
    manifests = []
    for doc in parsed:
        for tpl in doc["spec"]["templates"]:
            if tpl["name"] == "gordo-server-deployment":
                manifests.append(yaml.safe_load(tpl["resource"]["manifest"]))
    assert len(manifests) == 2
    for dep in manifests:
        spec = dep["spec"]
        assert "replicas" not in spec  # the autoscaler owns scaling
        assert spec["strategy"]["rollingUpdate"]["maxUnavailable"] == 0
        container = spec["template"]["spec"]["containers"][0]
        env = {e["name"]: e.get("value") for e in container["env"]}
        # file-based (inlining 10k names would blow k8s object limits);
        # stage-config writes the WHOLE project's list to this path
        assert env["EXPECTED_MODELS_FILE"].endswith("expected-models.json")
        assert container["readinessProbe"]["httpGet"]["path"] == "/readiness"
    # identical across docs: whichever doc applies last changes nothing
    assert manifests[0] == manifests[1]
    # and stage-config writes the full 35-machine expectation in BOTH docs
    import json as _json

    for doc in parsed:
        stage = next(
            t for t in doc["spec"]["templates"] if t["name"] == "stage-config"
        )
        body = stage["script"]["source"]
        marker_end = body.index("GORDO_TPU_EXPECTED_EOF") + len(
            "GORDO_TPU_EXPECTED_EOF"
        )
        start = body.index("\n", marker_end) + 1
        end = body.index("GORDO_TPU_EXPECTED_EOF", start)
        expected = _json.loads(body[start:end].strip())
        assert len(expected) == 35
