"""
Chaos tests: fleet builds under the deterministic fault-injection harness
(``GORDO_TPU_FAULT_PLAN``, util/faults.py).

The headline scenario mirrors the reference's blast-radius guarantee: with
one pod per machine, a bad sensor feed killed one pod. Here 12 machines
train in one process under a plan injecting transient fetch failures,
a permanent fetch failure, NaN-poisoned data, and a device OOM on the
bucket's first compile — and the build must degrade machine-by-machine:
exactly the genuinely-bad machines quarantined (reasons recorded in
BuildMetadata), byte-identical artifacts for the rest vs a fault-free run,
and the documented partial-success exit code from the CLI.
"""

import json
import pickle

import numpy as np
import pytest
import yaml

from gordo_tpu import serializer
from gordo_tpu.parallel import BatchedModelBuilder
from gordo_tpu.util import faults
from gordo_tpu.workflow.normalized_config import NormalizedConfig

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_fault_state(monkeypatch):
    """Each test gets a fresh fault plan (counters re-armed) and instant
    backoff; the plan env never leaks between tests."""
    monkeypatch.delenv(faults.PLAN_ENV, raising=False)
    monkeypatch.setenv("GORDO_TPU_FAULT_BACKOFF_BASE", "0")
    faults.reset_plan()
    yield
    faults.reset_plan()


def _machine_block(name, n_tags=4):
    tags = "".join(f"\n      - {name}-tag-{j}" for j in range(n_tags))
    return f"""
  - name: {name}
    dataset:
      tags:{tags}
      train_start_date: '2019-01-01T00:00:00+00:00'
      train_end_date: '2019-01-03T00:00:00+00:00'
      data_provider: {{type: RandomDataProvider}}
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        require_thresholds: true
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
            - sklearn.preprocessing.MinMaxScaler
            - gordo_tpu.models.models.AutoEncoder:
                kind: feedforward_hourglass
                epochs: 1
"""


def _machines(prefix, n):
    cfg = "machines:" + "".join(_machine_block(f"{prefix}-{i}") for i in range(n))
    return NormalizedConfig(yaml.safe_load(cfg), project_name="chaos").machines


def _set_plan(monkeypatch, rules):
    monkeypatch.setenv(faults.PLAN_ENV, json.dumps({"rules": rules}))
    faults.reset_plan()


# ------------------------------------------------------- headline scenario
def test_chaos_fleet_degrades_machine_by_machine(monkeypatch):
    """12-machine fleet under the full fault plan: transient fetch failures
    on 3 machines, a permanent fetch failure on 1, NaN-poisoned data on 1,
    and an injected OOM on the bucket's first compile. Exactly the 2
    genuinely-bad machines are quarantined with reasons in BuildMetadata;
    the other 10 produce byte-identical artifacts vs a fault-free run."""
    machines = _machines("fm", 12)

    # chunk pinned to the mesh size: the compiled dispatch shape is then
    # invariant to fleet composition, quarantine, and OOM bisection, which
    # is what makes artifacts bitwise-reproducible across degraded builds
    # (vmap lanes are bitwise-independent of bucket MEMBERSHIP at any
    # chunk, but XLA may round differently across compiled WIDTHS —
    # docs/robustness.md "Determinism")
    chunk = 8

    # fault-free reference run
    baseline = {
        m.name: pickle.dumps(model)
        for model, m in BatchedModelBuilder(machines, chunk_size=chunk).build()
    }
    assert len(baseline) == 12

    _set_plan(
        monkeypatch,
        [
            {"site": "data_fetch", "machine": "fm-1", "times": 2,
             "error": "transient"},
            {"site": "data_fetch", "machine": "fm-3", "times": 2,
             "error": "transient"},
            {"site": "data_fetch", "machine": "fm-5", "times": 1,
             "error": "transient"},
            {"site": "data_fetch", "machine": "fm-7", "times": -1,
             "error": "permanent"},
            {"site": "poison_nan", "machine": "fm-9"},
            {"site": "bucket_compile", "machine": "fm-0", "times": 1,
             "error": "resource_exhausted"},
        ],
    )
    builder = BatchedModelBuilder(machines, chunk_size=chunk)
    results = builder.build()

    built = {m.name: pickle.dumps(model) for model, m in results}
    assert sorted(built) == sorted(set(baseline) - {"fm-7", "fm-9"})

    # exactly the two genuinely-bad machines quarantined, with reasons
    by_name = {r.machine: r for r in builder.quarantine_records}
    assert set(by_name) == {"fm-7", "fm-9"}
    assert by_name["fm-7"].stage == faults.STAGE_DATA_FETCH
    assert by_name["fm-7"].reason == "permanent_fetch_failure"
    assert by_name["fm-9"].stage == faults.STAGE_DATA_VALIDATION
    assert by_name["fm-9"].reason == "non_finite_data"
    # ... and the reasons land in the quarantined machines' BuildMetadata
    for machine_out in builder.quarantined:
        fault_domain = machine_out.metadata.build_metadata.fault_domain
        assert fault_domain["quarantined"] is True
        assert fault_domain["stage"] == by_name[machine_out.name].stage
        assert fault_domain["reason"] == by_name[machine_out.name].reason

    # byte-identical artifacts for every surviving machine
    for name, blob in built.items():
        assert blob == baseline[name], f"artifact for {name} drifted"

    # the machines that recovered through retries record their attempts
    recovered = {
        m.name: m.metadata.build_metadata.fault_domain
        for _, m in results
        if m.metadata.build_metadata.fault_domain
    }
    assert recovered == {
        "fm-1": {"quarantined": False, "data_fetch_attempts": 3},
        "fm-3": {"quarantined": False, "data_fetch_attempts": 3},
        "fm-5": {"quarantined": False, "data_fetch_attempts": 2},
    }


# --------------------------------------------------------- recovery ladder
def test_transient_bucket_failure_retries_and_succeeds(monkeypatch):
    machines = _machines("tb", 2)
    _set_plan(
        monkeypatch,
        [{"site": "bucket_compile", "machine": "tb-0", "times": 1,
          "error": "transient"}],
    )
    builder = BatchedModelBuilder(machines)
    results = builder.build()
    assert len(results) == 2
    assert builder.quarantine_records == []


def test_permanent_bucket_failure_falls_back_to_serial(monkeypatch):
    """A bucket failure that is neither OOM nor transient ends in the
    last-resort ladder rung: per-machine serial ModelBuilder builds."""
    machines = _machines("pb", 2)
    _set_plan(
        monkeypatch,
        [{"site": "bucket_compile", "machine": "pb-0", "times": -1,
          "error": "permanent"}],
    )
    builder = BatchedModelBuilder(machines)
    results = builder.build()
    assert len(results) == 2
    assert builder.quarantine_records == []
    for model, machine_out in results:
        md = machine_out.metadata.build_metadata.model
        assert md.cross_validation.scores  # a real build, not a stub


def test_oom_bisection_recurses_to_singletons(monkeypatch):
    """Repeated OOM bisects down to single-machine buckets; a singleton that
    still OOMs falls back to the serial builder rather than aborting."""
    machines = _machines("ob", 4)
    _set_plan(
        monkeypatch,
        [{"site": "bucket_compile", "machine": "ob-0", "times": 3,
          "error": "resource_exhausted"}],
    )
    builder = BatchedModelBuilder(machines)
    results = builder.build()
    assert len(results) == 4
    assert builder.quarantine_records == []


def test_diverged_machine_is_quarantined(monkeypatch):
    machines = _machines("dv", 2)
    _set_plan(monkeypatch, [{"site": "diverge", "machine": "dv-1"}])
    builder = BatchedModelBuilder(machines)
    results = builder.build()
    assert [m.name for _, m in results] == ["dv-0"]
    [record] = builder.quarantine_records
    assert record.machine == "dv-1"
    assert record.stage == faults.STAGE_TRAINING
    assert record.reason == "diverged"


def test_fail_fast_restores_abort_on_first_fault(monkeypatch):
    machines = _machines("ff", 2)
    _set_plan(
        monkeypatch,
        [{"site": "data_fetch", "machine": "ff-0", "times": -1,
          "error": "permanent"}],
    )
    builder = BatchedModelBuilder(machines, fail_fast=True)
    with pytest.raises(faults.PermanentFault):
        builder.build()


def test_fail_fast_raises_on_poisoned_data(monkeypatch):
    machines = _machines("fp", 1)
    _set_plan(monkeypatch, [{"site": "poison_nan", "machine": "fp-0"}])
    builder = BatchedModelBuilder(machines, fail_fast=True)
    with pytest.raises(faults.NonFiniteDataError):
        builder.build()


# ----------------------------------------------------------- cache resume
def test_corrupt_cache_entry_is_evicted_and_rebuilt(tmp_path):
    """A truncated/corrupt cached model.pkl must not kill a resuming fleet
    build: the registry entry is evicted and the machine rebuilt."""
    machines = _machines("cc", 2)
    out_dir = str(tmp_path / "models")
    reg_dir = str(tmp_path / "registry")
    BatchedModelBuilder(
        machines, output_dir=out_dir, model_register_dir=reg_dir
    ).build()

    # corrupt one cached artifact in place
    corrupt_path = tmp_path / "models" / "cc-0" / "model.pkl"
    corrupt_path.write_bytes(b"\x80\x04 truncated garbage")

    builder = BatchedModelBuilder(
        machines, output_dir=out_dir, model_register_dir=reg_dir
    )
    results = builder.build()
    assert len(results) == 2
    assert builder.quarantine_records == []
    # the artifact was rebuilt in place and loads again
    model = serializer.load(str(tmp_path / "models" / "cc-0"))
    assert model is not None
    # the clean machine still came from cache
    cached = [
        m for _, m in results
        if m.metadata.user_defined.get("build-metadata", {}).get("from_cache")
    ]
    assert [m.name for m in cached] == ["cc-1"]


# ------------------------------------------------------------ CLI contract
def _write_config(tmp_path, prefix, n):
    cfg = "machines:" + "".join(
        _machine_block(f"{prefix}-{i}") for i in range(n)
    )
    config_file = tmp_path / "config.yaml"
    config_file.write_text(cfg)
    return str(config_file)


def test_cli_partial_build_exit_code(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import gordo

    config_file = _write_config(tmp_path, "cp", 2)
    _set_plan(
        monkeypatch,
        [{"site": "data_fetch", "machine": "cp-1", "times": -1,
          "error": "permanent"}],
    )
    report_file = tmp_path / "quarantine.json"
    result = CliRunner().invoke(
        gordo,
        [
            "batch-build", config_file,
            "--output-dir", str(tmp_path / "models"),
            "--quarantine-report-file", str(report_file),
        ],
    )
    assert result.exit_code == faults.EXIT_PARTIAL, result.output
    assert "quarantined: cp-1" in result.output
    assert (tmp_path / "models" / "cp-0" / "model.pkl").exists()
    report = json.loads(report_file.read_text())
    assert report["built"] == 1
    [record] = report["quarantined"]
    assert record["machine"] == "cp-1"
    assert record["stage"] == faults.STAGE_DATA_FETCH


def test_cli_none_built_exit_code(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import gordo

    config_file = _write_config(tmp_path, "cn", 1)
    _set_plan(
        monkeypatch,
        [{"site": "data_fetch", "machine": "cn-0", "times": -1,
          "error": "permanent"}],
    )
    result = CliRunner().invoke(
        gordo,
        ["batch-build", config_file, "--output-dir", str(tmp_path / "models")],
    )
    assert result.exit_code == faults.EXIT_NONE_BUILT, result.output


def test_cli_fail_fast_flag_aborts(tmp_path, monkeypatch):
    from click.testing import CliRunner

    from gordo_tpu.cli.cli import gordo

    config_file = _write_config(tmp_path, "cf", 2)
    _set_plan(
        monkeypatch,
        [{"site": "data_fetch", "machine": "cf-0", "times": -1,
          "error": "permanent"}],
    )
    result = CliRunner().invoke(
        gordo,
        [
            "batch-build", config_file,
            "--output-dir", str(tmp_path / "models"),
            "--fail-fast",
        ],
    )
    # generic exception exit code from the exceptions reporter, not the
    # partial-success contract: fail-fast aborts
    assert result.exit_code == 1, result.output


# ----------------------------------------------------- serial-path parity
def test_serial_builder_retries_transient_fetch(monkeypatch, tmp_path):
    from gordo_tpu.builder import ModelBuilder

    [machine] = _machines("sr", 1)
    _set_plan(
        monkeypatch,
        [{"site": "data_fetch", "machine": "sr-0", "times": 2,
          "error": "transient"}],
    )
    model, machine_out = ModelBuilder(machine).build()
    assert model is not None
    fault_domain = machine_out.metadata.build_metadata.fault_domain
    assert fault_domain == {"quarantined": False, "data_fetch_attempts": 3}


def test_serial_builder_rejects_poisoned_data(monkeypatch):
    from gordo_tpu.builder import ModelBuilder

    [machine] = _machines("sp", 1)
    _set_plan(monkeypatch, [{"site": "poison_nan", "machine": "sp-0"}])
    with pytest.raises(faults.NonFiniteDataError):
        ModelBuilder(machine).build()


# ----------------------------------------------------- serving resilience
def _assert_payload_close(got, want, path=""):
    """Structural equality with approximate float leaves — fused widths
    vary run to run and XLA float32 is not bitwise-stable across vmap
    widths (same tolerance rationale as test_batcher.py)."""
    import numpy as np

    assert type(got) is type(want), f"{path}: {type(got)} != {type(want)}"
    if isinstance(got, dict):
        assert got.keys() == want.keys(), f"{path}: keys differ"
        for k in got:
            _assert_payload_close(got[k], want[k], f"{path}.{k}")
    elif isinstance(got, list):
        assert len(got) == len(want), f"{path}: lengths differ"
        for i, (g, w) in enumerate(zip(got, want)):
            _assert_payload_close(g, w, f"{path}[{i}]")
    elif isinstance(got, float):
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                   err_msg=path)
    else:
        assert got == want, f"{path}: {got!r} != {want!r}"


def test_chaos_serving_wedge_and_poison_degrade_only_themselves(
    monkeypatch, model_collection_directory, trained_model_directories,
    gordo_project, gordo_name, second_gordo_name, X_payload,
):
    """Serving headline scenario: 12 concurrent clients against one
    in-process server with the cross-model batcher on, while the fault
    plan (a) wedges one fused device call for 2.5s and (b) NaN-poisons
    every predict of one model. Blast radius must be exactly the faults'
    own: every healthy-model request eventually succeeds with correct
    values (shed 503s and deadline 504s are retried), the circuit breaker
    opens for the poisoned model only, /healthcheck flips to 503 exactly
    while the dispatcher is wedged, and the shed/deadline/breaker/abandon
    counters land in /metrics."""
    import threading
    import time

    from gordo_tpu.observability import metrics as metric_catalog
    from gordo_tpu.server import batcher as batcher_mod
    from gordo_tpu.server import resilience
    from gordo_tpu.server import utils as server_utils
    from gordo_tpu.server.server import build_app
    from gordo_tpu.server.utils import dataframe_to_dict

    poisoned, healthy = gordo_name, second_gordo_name

    resilience.reset_for_tests()
    server_utils.clear_model_caches()
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setenv("GORDO_TPU_MAX_INFLIGHT", "4")
    monkeypatch.setenv("GORDO_TPU_RETRY_AFTER_S", "1")
    monkeypatch.setenv("GORDO_TPU_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("GORDO_TPU_BREAKER_COOLDOWN_S", "60")
    monkeypatch.setenv("GORDO_TPU_WATCHDOG_S", "0.2")
    monkeypatch.setenv("GORDO_TPU_VALIDATE_OUTPUT", "1")

    app = build_app({
        "MODEL_COLLECTION_DIR": model_collection_directory,
        "ENABLE_PROMETHEUS": True,
        "PROJECT": "gordo-test",
    })
    body = json.dumps({"X": dataframe_to_dict(X_payload)}).encode()

    def post(client, name, headers=None):
        return client.post(
            f"/gordo/v0/{gordo_project}/{name}/prediction",
            data=body, content_type="application/json",
            headers=headers or {},
        )

    # fault-free warm pass (loads models, compiles the width-1 fused
    # program, records the correct healthy payload) BEFORE arming faults
    # or deadlines
    warm = post(app.test_client(), healthy)
    assert warm.status_code == 200, warm.data
    baseline_data = json.loads(warm.data)["data"]
    assert post(app.test_client(), poisoned).status_code == 200

    # deadline armed only for the faulted phase: queued requests must
    # abandon behind the wedge instead of waiting it out
    monkeypatch.setenv("GORDO_TPU_DEADLINE_MS", "2000")
    _set_plan(monkeypatch, [
        {"site": "serve_device_call", "times": 1, "error": "wedge",
         "seconds": 2.5},
        {"site": "serve_poison_nan", "machine": poisoned},
    ])

    shed_before = metric_catalog.SERVER_SHED.value(reason="max_inflight")
    abandoned_before = metric_catalog.BATCHER_ABANDONED.value()

    outcomes = {}
    saw_shed = []
    saw_deadline = []

    def client_thread(idx, name):
        client = app.test_client()
        deadline = time.monotonic() + 60
        got_500 = False
        while time.monotonic() < deadline:
            resp = post(client, name)
            if resp.status_code == 200:
                outcomes[idx] = ("ok", json.loads(resp.data)["data"])
                return
            payload = resp.get_json()
            if resp.status_code == 503 and payload.get("model") == name:
                # breaker fast-fail: terminal for a poisoned model
                assert resp.headers.get("Retry-After") is not None
                outcomes[idx] = ("breaker", got_500)
                return
            if resp.status_code == 503:
                assert payload.get("reason") == "max_inflight"
                assert resp.headers.get("Retry-After") is not None
                saw_shed.append(idx)
            elif resp.status_code == 504:
                saw_deadline.append(idx)
            elif resp.status_code == 500:
                got_500 = True  # the poisoned lane's typed failure
            else:
                outcomes[idx] = ("unexpected", resp.status_code, payload)
                return
            time.sleep(0.05)
        outcomes[idx] = ("timeout",)

    threads = [
        threading.Thread(target=client_thread, args=(i, healthy))
        for i in range(8)
    ] + [
        threading.Thread(target=client_thread, args=(8 + i, poisoned))
        for i in range(4)
    ]
    for t in threads:
        t.start()

    # while the fused call is wedged the device watchdog must flip
    # /healthcheck to 503 (and back to 200 once the wedge clears)
    health = app.test_client()
    saw_watchdog_503 = False
    probe_deadline = time.monotonic() + 30
    while any(t.is_alive() for t in threads):
        if health.get("/healthcheck").status_code == 503:
            saw_watchdog_503 = True
        if time.monotonic() > probe_deadline:
            break
        time.sleep(0.05)
    for t in threads:
        t.join(timeout=90)
    assert not any(t.is_alive() for t in threads)

    # blast radius: every healthy client succeeded with correct values...
    for i in range(8):
        kind = outcomes[i][0]
        assert kind == "ok", f"healthy client {i}: {outcomes[i]}"
        _assert_payload_close(outcomes[i][1], baseline_data)
    # ...every poisoned client ended on the open breaker
    for i in range(8, 12):
        assert outcomes[i][0] == "breaker", f"poisoned client {i}: {outcomes[i]}"
    assert any(outcomes[i][1] for i in range(8, 12)), (
        "no poisoned client ever observed the typed 500 that opened "
        "the breaker"
    )

    # breaker open for the poisoned model ONLY
    assert resilience.breaker_for(poisoned).state == resilience.OPEN
    assert resilience.breaker_for(healthy).state == resilience.CLOSED
    assert (
        metric_catalog.BREAKER_STATE.value(model=poisoned)
        == resilience.OPEN
    )

    # the wedge was observed end to end: healthcheck flipped while the
    # dispatcher was stuck and recovered afterwards
    assert saw_watchdog_503, "watchdog never flipped /healthcheck to 503"
    assert health.get("/healthcheck").status_code == 200

    # load was actually shed and deadlines actually expired (12 clients
    # vs MAX_INFLIGHT=4 and a 2.5s wedge vs a 2s budget guarantee both)
    assert metric_catalog.SERVER_SHED.value(reason="max_inflight") > shed_before
    assert metric_catalog.BATCHER_ABANDONED.value() > abandoned_before
    assert saw_shed and saw_deadline

    # the counters are a /metrics contract, not just process state
    metrics_text = app.test_client().get("/metrics").data.decode()
    for series in (
        "gordo_server_shed_total",
        "gordo_server_deadline_exceeded_total",
        "gordo_server_batcher_abandoned_total",
        "gordo_server_breaker_state",
        "gordo_server_breaker_opens_total",
        "gordo_server_watchdog_trips_total",
    ):
        assert series in metrics_text, f"{series} missing from /metrics"

    resilience.reset_for_tests()
    server_utils.clear_model_caches()
