"""Analytic FLOPs/MFU accounting (ops/flops.py) — the bench's MFU inputs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gordo_tpu.models.models import (
    AutoEncoder,
    LSTMAutoEncoder,
    TransformerAutoEncoder,
)
from gordo_tpu.ops import flops as flops_mod
from gordo_tpu.ops.nn import init_model_params, moe_aux_loss
from gordo_tpu.models.spec import MoEBlock


def _spec(est):
    return est.build_spec(8, 8)


@pytest.mark.parametrize(
    "est",
    [
        AutoEncoder(kind="feedforward_hourglass"),
        LSTMAutoEncoder(
            kind="lstm_symmetric", dims=[64, 32], funcs=["tanh", "tanh"],
            lookback_window=16,
        ),
        TransformerAutoEncoder(kind="transformer_model", lookback_window=16),
        TransformerAutoEncoder(
            kind="moe_transformer_model", lookback_window=16, num_experts=4
        ),
    ],
    ids=["hourglass", "lstm", "transformer", "moe"],
)
def test_param_count_matches_initialized_tree(est):
    """The layer-walk parameter count must match the real pytree — the same
    walk prices the FLOPs, so a drift here means wrong MFU."""
    spec = _spec(est)
    params = init_model_params(jax.random.PRNGKey(0), spec)
    # the walk counts matmul/recurrent weights + their biases; layernorm
    # scales/biases and attention biases are excluded (negligible FLOPs).
    counted = flops_mod.spec_param_count(spec)
    actual = sum(int(np.prod(leaf.shape)) for leaf in jax.tree_util.tree_leaves(params))
    assert counted <= actual
    assert counted >= 0.7 * actual, (counted, actual)


def test_forward_flops_scale_with_window_and_width():
    lstm16 = _spec(LSTMAutoEncoder(
        kind="lstm_symmetric", dims=[64, 32], funcs=["tanh", "tanh"],
        lookback_window=16,
    ))
    lstm64 = _spec(LSTMAutoEncoder(
        kind="lstm_symmetric", dims=[64, 32], funcs=["tanh", "tanh"],
        lookback_window=64,
    ))
    f16 = flops_mod.forward_flops_per_sample(lstm16)
    f64 = flops_mod.forward_flops_per_sample(lstm64)
    assert f16 > 0
    # LSTM cost is linear in T
    np.testing.assert_allclose(f64 / f16, 4.0, rtol=0.01)

    # attention adds a quadratic-in-T term: more than 4x when T quadruples
    tr16 = _spec(TransformerAutoEncoder(kind="transformer_model", lookback_window=16))
    tr64 = _spec(TransformerAutoEncoder(kind="transformer_model", lookback_window=64))
    assert (
        flops_mod.forward_flops_per_sample(tr64)
        > 4.0 * flops_mod.forward_flops_per_sample(tr16)
    )


def test_cv_build_flops_composition():
    """3 folds + final fit, training 3x forward, remat 4x."""
    spec = _spec(AutoEncoder(kind="feedforward_hourglass"))
    fwd = flops_mod.forward_flops_per_sample(spec)
    total = flops_mod.cv_build_flops(spec, n_rows=400, epochs=2, n_splits=3)
    # train work: folds of 100/200/300 rows + full 400, 2 epochs, 3x fwd;
    # predict work: 3 x 100-row fold predictions
    expected = 3 * fwd * (100 + 200 + 300 + 400) * 2 + fwd * 300
    np.testing.assert_allclose(total, expected, rtol=1e-9)

    import dataclasses

    remat = dataclasses.replace(spec, remat=True)
    assert flops_mod.training_flops_per_sample(remat) == pytest.approx(
        4 / 3 * flops_mod.training_flops_per_sample(spec)
    )


def test_mfu_and_peak_lookup():
    assert flops_mod.chip_peak_flops("TPU v4") == 275e12
    assert flops_mod.chip_peak_flops("TPU v5 lite") == 394e12
    assert flops_mod.chip_peak_flops("cpu-whatever") is None
    assert flops_mod.mfu(1e12, 1.0, "TPU v4") == pytest.approx(1e12 / 275e12)
    # aggregate peak scales with device count
    assert flops_mod.mfu(1e12, 1.0, "TPU v4", n_devices=4) == pytest.approx(
        1e12 / (4 * 275e12)
    )
    # ISSUE 9: unknown chips fall back to the measured GEMM peak instead
    # of returning None — CPU bench records now carry a real MFU
    assert flops_mod.mfu(1e12, 1.0, "unknown") is not None


def test_peak_env_override(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PEAK_FLOPS", "1e15")
    assert flops_mod.chip_peak_flops("anything") == 1e15


def test_peak_source_tags(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PEAK_FLOPS", raising=False)
    assert flops_mod.peak_flops_with_source("TPU v4") == (275e12, "table")
    peak, source = flops_mod.peak_flops_with_source("cpu-whatever")
    assert source == "measured" and peak > 0
    monkeypatch.setenv("GORDO_TPU_PEAK_FLOPS", "1e15")
    assert flops_mod.peak_flops_with_source("anything") == (1e15, "env")


def test_mfu_with_source_threads_the_tag(monkeypatch):
    monkeypatch.delenv("GORDO_TPU_PEAK_FLOPS", raising=False)
    value, source = flops_mod.mfu_with_source(1e12, 1.0, "TPU v4")
    assert value == pytest.approx(1e12 / 275e12)
    assert source == "table"
    value, source = flops_mod.mfu_with_source(1e9, 1.0, "cpu-whatever")
    assert source == "measured" and value is not None and value > 0
    # degenerate wall: no MFU, but the source tag still says which peak
    # would have been used
    value, source = flops_mod.mfu_with_source(1e9, 0.0, "TPU v4")
    assert value is None and source == "table"


def test_measured_peak_cached_and_positive():
    first = flops_mod.measured_peak_flops()
    assert first is not None and first > 0
    # in-process memo: the second call must not re-time the GEMM
    assert flops_mod.measured_peak_flops() == first


def test_serving_peak_flops_reports_a_peak():
    peak, source = flops_mod.serving_peak_flops()
    assert peak is not None and peak > 0
    assert source in ("env", "table", "measured")


# --------------------------------------------------------- MoE aux loss
def test_moe_aux_loss_uniform_vs_collapsed():
    """Switch load-balancing loss: 1.0 under uniform routing, -> E under
    full collapse (every token to one expert)."""
    layer = MoEBlock(d_model=8, num_experts=4)
    n = 64
    uniform = jnp.tile(jnp.full((1, 4), 0.25), (n, 1))
    # perturb so argmax spreads evenly across experts
    bump = jax.nn.one_hot(jnp.arange(n) % 4, 4) * 0.01
    val_uniform = float(moe_aux_loss(layer, uniform + bump))
    assert val_uniform == pytest.approx(1.0, rel=0.05)

    collapsed = jnp.tile(jnp.asarray([[0.97, 0.01, 0.01, 0.01]]), (n, 1))
    val_collapsed = float(moe_aux_loss(layer, collapsed))
    assert val_collapsed > 3.5  # ~ E * P_hot


def test_moe_aux_loss_reaches_training_penalty():
    """apply_model threads the weighted aux loss into the penalty the
    training loss adds — the mechanism that prevents expert collapse."""
    import dataclasses

    from gordo_tpu.ops.nn import apply_model

    est = TransformerAutoEncoder(
        kind="moe_transformer_model", lookback_window=8, num_experts=4
    )
    spec = est.build_spec(4, 4)
    params = init_model_params(jax.random.PRNGKey(0), spec)
    x = jax.random.uniform(jax.random.PRNGKey(1), (6, 8, 4))
    _, penalty = apply_model(spec, params, x)
    assert float(penalty) > 0.0

    moe_idx = [
        i for i, l in enumerate(spec.layers) if isinstance(l, MoEBlock)
    ]
    zeroed_layers = tuple(
        dataclasses.replace(l, aux_loss_weight=0.0) if isinstance(l, MoEBlock) else l
        for l in spec.layers
    )
    spec0 = dataclasses.replace(spec, layers=zeroed_layers)
    _, penalty0 = apply_model(spec0, params, x)
    assert float(penalty0) < float(penalty)
    assert moe_idx  # the factory really emits MoE blocks
