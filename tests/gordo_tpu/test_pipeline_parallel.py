"""
Pipeline parallelism (GPipe over the `pipe` mesh axis) on the 8-virtual-
device CPU mesh. Contract: the pipelined schedule is numerically the
sequential block loop (same math, different placement), and pipelined
specs keep off both vmapping paths like ring/TP.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gordo_tpu.models.models import TransformerAutoEncoder
from gordo_tpu.models.spec import TransformerBlock
from gordo_tpu.ops.nn import (
    _apply_transformer_block,
    apply_model,
    init_model_params,
)
from gordo_tpu.parallel.pipeline_parallel import (
    make_pipeline_blocks_fn,
    pp_degree,
    prepare_pp_spec,
)

N_TAGS = 4
PP_KW = dict(
    kind="transformer_model",
    lookback_window=16,
    d_model=16,
    num_heads=2,
    ff_dim=32,
    num_blocks=4,
    epochs=2,
    batch_size=32,
)


@pytest.mark.parametrize("n_stages,n_blocks", [(2, 4), (4, 4), (4, 8)])
def test_pipeline_matches_sequential(n_stages, n_blocks):
    layer = TransformerBlock(d_model=16, num_heads=2, ff_dim=32, causal=True,
                             attention_impl="xla")
    rng = jax.random.PRNGKey(0)
    from gordo_tpu.ops.nn import init_transformer_block

    block_params = [
        init_transformer_block(k, 16, layer)
        for k in jax.random.split(rng, n_blocks)
    ]
    x = jnp.asarray(
        np.random.RandomState(1).randn(8, 12, 16).astype(np.float32)
    )
    sequential = x
    for p in block_params:
        sequential = _apply_transformer_block(layer, p, sequential)

    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves).reshape(
            (n_stages, n_blocks // n_stages) + leaves[0].shape
        ),
        *block_params,
    )
    fn = make_pipeline_blocks_fn(layer, n_stages, n_blocks // n_stages, n_stages)
    out = fn(stacked, x)
    np.testing.assert_allclose(out, sequential, rtol=2e-4, atol=2e-6)


def test_pipeline_grad_matches_sequential():
    layer = TransformerBlock(d_model=16, num_heads=2, ff_dim=32,
                             attention_impl="xla")
    from gordo_tpu.ops.nn import init_transformer_block

    block_params = [
        init_transformer_block(k, 16, layer)
        for k in jax.random.split(jax.random.PRNGKey(2), 4)
    ]
    x = jnp.asarray(
        np.random.RandomState(3).randn(4, 8, 16).astype(np.float32)
    )

    def seq_loss(params):
        h = x
        for p in params:
            h = _apply_transformer_block(layer, p, h)
        return jnp.sum(h ** 2)

    def pipe_loss(params):
        stacked = jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves).reshape(
                (4, 1) + leaves[0].shape
            ),
            *params,
        )
        return jnp.sum(make_pipeline_blocks_fn(layer, 4, 1, 4)(stacked, x) ** 2)

    g_seq = jax.grad(seq_loss)(block_params)
    g_pipe = jax.grad(pipe_loss)(block_params)
    for a, b in zip(jax.tree_util.tree_leaves(g_seq),
                    jax.tree_util.tree_leaves(g_pipe)):
        np.testing.assert_allclose(a, b, rtol=1e-3, atol=5e-5)


def test_pp_model_trains_and_matches_sequential():
    X = np.random.RandomState(5).rand(96, N_TAGS).astype(np.float32)
    np.random.seed(11)
    plain = TransformerAutoEncoder(**PP_KW)
    plain.fit(X, X)
    np.random.seed(11)
    piped = TransformerAutoEncoder(pipeline_parallel=4, **PP_KW)
    piped.fit(X, X)
    assert pp_degree(piped.spec_) == 4
    np.testing.assert_allclose(
        plain.history["loss"], piped.history["loss"], rtol=2e-4
    )
    np.testing.assert_allclose(
        plain.predict(X), piped.predict(X), rtol=2e-4, atol=2e-5
    )


def test_pp_fallback_when_batch_indivisible():
    """A batch not divisible into microbatches silently runs sequential —
    same math, no crash (predict tails, odd sizes)."""
    spec = TransformerAutoEncoder(
        pipeline_parallel=4, **PP_KW
    ).build_spec(N_TAGS, N_TAGS)
    params = init_model_params(jax.random.PRNGKey(0), spec)
    x = jnp.asarray(np.random.RandomState(0).rand(7, 16, N_TAGS), jnp.float32)
    windows = jnp.stack([x[0, :, :]] * 3)  # batch 3: not divisible by 4
    out, _ = apply_model(spec, params, windows)
    assert np.all(np.isfinite(out))


def test_pp_validation():
    with pytest.raises(ValueError, match="divisible"):
        TransformerAutoEncoder(
            pipeline_parallel=4, **{**PP_KW, "num_blocks": 3}
        ).build_spec(N_TAGS, N_TAGS)
    with pytest.raises(ValueError, match="cannot run inside"):
        TransformerAutoEncoder(
            pipeline_parallel=4, **{**PP_KW, "attention": "flash"}
        ).build_spec(N_TAGS, N_TAGS)
    with pytest.raises(ValueError, match="cannot combine"):
        TransformerAutoEncoder(
            pipeline_parallel=2, tensor_parallel=2, **PP_KW
        ).build_spec(N_TAGS, N_TAGS)
    spec = TransformerAutoEncoder(**PP_KW).build_spec(N_TAGS, N_TAGS)
    assert prepare_pp_spec(spec) is spec  # off -> untouched


def test_pp_machines_take_serial_fallback_and_skip_batcher(monkeypatch):
    from gordo_tpu.machine import Machine
    from gordo_tpu.parallel.batch_trainer import _plan_machine
    from gordo_tpu.server import batcher as batcher_mod
    from gordo_tpu.server.batcher import maybe_submit

    config = {
        "name": "pp-machine",
        "dataset": {
            "type": "RandomDataset",
            "tags": [f"pp-tag-{i}" for i in range(N_TAGS)],
            "train_start_date": "2019-01-01T00:00:00+00:00",
            "train_end_date": "2019-01-08T00:00:00+00:00",
        },
        "model": {
            "gordo_tpu.models.models.TransformerAutoEncoder": {
                **{k: v for k, v in PP_KW.items() if k != "kind"},
                "kind": "transformer_model",
                "pipeline_parallel": 4,
            }
        },
    }
    machine = Machine.from_config(config, project_name="pp-test")
    assert _plan_machine(machine) is None

    spec = TransformerAutoEncoder(
        pipeline_parallel=4, **PP_KW
    ).build_spec(N_TAGS, N_TAGS)
    # batching ON and submit booby-trapped: the pp guard must return None
    # before the queue is ever touched
    monkeypatch.setenv("GORDO_TPU_SERVING_BATCH", "1")
    monkeypatch.setattr(batcher_mod, "_batcher", None)
    monkeypatch.setattr(
        batcher_mod.CrossModelBatcher,
        "submit",
        lambda self, *a: pytest.fail("pp spec reached the batcher queue"),
    )
    assert maybe_submit(spec, None, None) is None


def test_pp_rejects_indivisible_batch_size():
    X = np.random.RandomState(0).rand(64, N_TAGS).astype(np.float32)
    model = TransformerAutoEncoder(
        pipeline_parallel=4, **{**PP_KW, "batch_size": 30}
    )
    with pytest.raises(ValueError, match="batch_size divisible"):
        model.fit(X, X)


def test_pp_remat_checkpoints_inside_pipeline():
    """remat + pipeline: the stage scan rematerializes block activations."""
    spec = TransformerAutoEncoder(
        pipeline_parallel=4, remat=True, **PP_KW
    ).build_spec(N_TAGS, N_TAGS)
    assert spec.remat and pp_degree(spec) == 4
    params = init_model_params(jax.random.PRNGKey(0), spec)
    x = jnp.asarray(np.random.RandomState(0).rand(8, 16, N_TAGS), jnp.float32)

    def loss(p):
        out, _ = apply_model(spec, p, x)
        return jnp.sum(out ** 2)

    jaxpr = str(jax.make_jaxpr(jax.grad(loss))(params))
    assert "remat" in jaxpr
    assert np.all(np.isfinite(jax.grad(loss)(params)[0]["kernel"]))


@pytest.mark.parametrize(
    "extra",
    [
        {"tensor_parallel": 8, "num_heads": 8},
        {"pipeline_parallel": 4},
        {"attention": "ring", "lookback_window": 16},
    ],
    ids=["tp", "pp", "ring"],
)
def test_axes_compose_with_bf16_and_remat(extra):
    """Every per-model axis must train finite under the MXU-native dtype
    and rematerialization — the combination real TPU configs use."""
    kwargs = {**PP_KW, "compute_dtype": "bfloat16", "remat": True, **extra}
    X = np.random.RandomState(9).rand(96, N_TAGS).astype(np.float32)
    model = TransformerAutoEncoder(**kwargs)
    model.fit(X, X)
    assert np.isfinite(model.history["loss"]).all()
    assert np.isfinite(model.predict(X)).all()


def test_moe_composes_with_bf16_and_remat():
    from tests.gordo_tpu.test_expert_parallel import MOE_KW

    X = np.random.RandomState(9).rand(96, N_TAGS).astype(np.float32)
    model = TransformerAutoEncoder(
        compute_dtype="bfloat16", remat=True, expert_parallel=8, **MOE_KW
    )
    model.fit(X, X)
    assert np.isfinite(model.history["loss"]).all()
    assert np.isfinite(model.predict(X)).all()
