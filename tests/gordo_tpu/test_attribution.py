"""
Latency attribution engine (ISSUE 17, layer 2): gated observe, epoch
windows, the budget-closing decomposition contract (rows sum EXACTLY to
the headline delta), mix-shift, shard merge, and phase-stat recovery
from the committed BENCH records (the --explain offline path).
"""

import json
import os

import pytest

from gordo_tpu.observability import attribution

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (
        "GORDO_TPU_PERF_ATTRIBUTION",
        "GORDO_TPU_PERF_SENTINEL",
        "GORDO_TPU_PERF_WINDOW_S",
    ):
        monkeypatch.delenv(var, raising=False)
    attribution.reset()
    yield
    attribution.reset()


# ------------------------------------------------------------ gated observe
def test_observe_is_noop_when_disabled():
    attribution.observe(
        "m", 0.010, {"decode": 0.002, "predict": 0.004}, now=1000.0
    )
    index = attribution.current_window_index(1000.0)
    assert attribution.window_stats(index) is None
    assert attribution.snapshot()["enabled"] is False


def test_sentinel_knob_also_enables_attribution(monkeypatch):
    """The sentinel feeds on these windows, so its knob opens this gate."""
    monkeypatch.setenv("GORDO_TPU_PERF_SENTINEL", "1")
    assert attribution.enabled() is True


# ----------------------------------------------------------- epoch windows
def test_observe_fills_epoch_windows(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PERF_ATTRIBUTION", "1")
    monkeypatch.setenv("GORDO_TPU_PERF_WINDOW_S", "100")
    for i in range(50):
        attribution.observe(
            "model-a", 0.010,
            {"decode": 0.002, "predict": 0.004, "encode": 0.001},
            now=1000.0 + i,
        )
    stats = attribution.window_stats(
        attribution.current_window_index(1000.0)
    )
    assert stats is not None
    assert stats["total"]["count"] == 50
    assert {"decode", "predict", "encode", "server_other"} <= set(
        stats["phases"]
    )
    assert stats["models"]["model-a"]["count"] == 50
    # server_other closes the in-request budget: 10 - (2+4+1) = ~3ms
    assert stats["phases"]["server_other"]["p50_ms"] == pytest.approx(
        3.0, rel=0.10
    )


def test_old_windows_expire(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PERF_ATTRIBUTION", "1")
    monkeypatch.setenv("GORDO_TPU_PERF_WINDOW_S", "100")
    attribution.observe("m", 0.010, {"decode": 0.002}, now=1000.0)
    old_index = attribution.current_window_index(1000.0)
    # five windows later the old one must have been dropped
    attribution.observe("m", 0.010, {"decode": 0.002}, now=1500.0)
    assert attribution.window_stats(old_index) is None


# ----------------------------------------------------------- decomposition
def _stats(p50, p99, phases):
    return {
        "total": {"p50_ms": p50, "p99_ms": p99},
        "phases": {
            name: {"p50_ms": value, "p99_ms": value}
            for name, value in phases.items()
        },
    }


def test_decomposition_rows_sum_exactly_to_headline():
    base = _stats(10.0, 20.0, {"decode": 2.0, "predict": 5.0, "encode": 1.0})
    cur = _stats(12.0, 40.0, {"decode": 2.0, "predict": 5.0, "encode": 21.0})
    decomp = attribution.decompose_stats(base, cur, "p99_ms")
    assert decomp["headline_delta_ms"] == pytest.approx(20.0)
    assert sum(r["delta_ms"] for r in decomp["rows"]) == pytest.approx(
        decomp["headline_delta_ms"]
    )
    rows = {r["name"]: r for r in decomp["rows"]}
    assert rows["encode"]["delta_ms"] == pytest.approx(20.0)
    assert rows["encode"]["share"] == pytest.approx(1.0)
    assert rows["decode"]["delta_ms"] == pytest.approx(0.0)


def test_walltime_splits_queue_from_server_other():
    """With request_walltime present, the derived rows split the delta
    into in-server remainder vs queue/transport — and still close the
    budget exactly."""
    base = _stats(
        10.0, 20.0,
        {"decode": 2.0, "predict": 5.0, "encode": 1.0,
         "request_walltime": 9.0},
    )
    cur = _stats(
        12.0, 35.0,
        {"decode": 2.0, "predict": 5.0, "encode": 1.0,
         "request_walltime": 9.5},
    )
    decomp = attribution.decompose_stats(base, cur, "p99_ms")
    names = {r["name"] for r in decomp["rows"]}
    assert "queue/transport" in names
    assert "server_other" in names
    assert "unattributed" not in names
    assert sum(r["delta_ms"] for r in decomp["rows"]) == pytest.approx(
        decomp["headline_delta_ms"]
    )
    rows = {r["name"]: r for r in decomp["rows"]}
    # walltime moved +0.5 with flat phases; the client total moved +15,
    # so queue/transport carries the other +14.5
    assert rows["server_other"]["delta_ms"] == pytest.approx(0.5)
    assert rows["queue/transport"]["delta_ms"] == pytest.approx(14.5)


def test_mix_shift_shift_share():
    base = {
        "a": {"count": 50, "mean_ms": 1.0},
        "b": {"count": 50, "mean_ms": 9.0},
    }
    cur = {
        "a": {"count": 10, "mean_ms": 1.0},
        "b": {"count": 90, "mean_ms": 9.0},
    }
    # b's share rose 0.4 at base-mean 9ms, a's fell 0.4 at 1ms
    assert attribution.mix_shift(base, cur) == pytest.approx(
        0.4 * 9.0 - 0.4 * 1.0
    )
    assert attribution.mix_shift(None, cur) is None
    assert attribution.mix_shift(base, {}) is None


def test_live_decomposition_current_vs_closed_window(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PERF_ATTRIBUTION", "1")
    monkeypatch.setenv("GORDO_TPU_PERF_WINDOW_S", "100")
    for i in range(40):
        attribution.observe(
            "m", 0.010, {"encode": 0.001}, now=1000.0 + i
        )
    for i in range(40):
        attribution.observe(
            "m", 0.030, {"encode": 0.021}, now=1100.0 + i
        )
    decomp = attribution.live_decomposition("p50_ms", now=1100.0)
    assert decomp is not None
    assert decomp["base_window"] == 10
    assert decomp["cur_window"] == 11
    rows = {r["name"]: r for r in decomp["rows"]}
    # the +20ms move is the encode phase (log-bucket resolution ~1.6%)
    assert rows["encode"]["delta_ms"] == pytest.approx(20.0, rel=0.2)
    assert sum(r["delta_ms"] for r in decomp["rows"]) == pytest.approx(
        decomp["headline_delta_ms"]
    )


def test_format_decomposition_renders_table():
    base = _stats(10.0, 20.0, {"decode": 2.0, "predict": 5.0, "encode": 1.0})
    cur = _stats(12.0, 40.0, {"decode": 2.0, "predict": 5.0, "encode": 21.0})
    lines = attribution.format_decomposition(
        attribution.decompose_stats(base, cur, "p99_ms")
    )
    assert any("headline" in line for line in lines)
    assert any(line.lstrip().startswith("encode") for line in lines)


# -------------------------------------------------------------- fleet merge
def test_shard_payload_merge_doubles_counts(monkeypatch):
    monkeypatch.setenv("GORDO_TPU_PERF_ATTRIBUTION", "1")
    monkeypatch.setenv("GORDO_TPU_PERF_WINDOW_S", "100")
    for i in range(10):
        attribution.observe(
            "m", 0.010, {"decode": 0.002}, now=1000.0 + i
        )
    payload = attribution.shard_payload()
    assert payload
    merged = attribution.merge_payloads([(1, payload), (2, payload)])
    index = str(attribution.current_window_index(1000.0))
    assert merged[index]["models"]["m"][0] == 20
    total = merged[index]["phases"]["total"]
    from gordo_tpu.observability.latency import LatencyHistogram

    assert LatencyHistogram.from_dict(total).count == 20


# ------------------------------------------------- committed BENCH records
@pytest.mark.parametrize("name", ["BENCH_r08.json", "BENCH_r09.json"])
def test_phase_stats_recoverable_from_committed_records(name):
    with open(os.path.join(REPO_ROOT, name)) as fh:
        record = json.load(fh)
    stats = attribution.phase_stats_from_record(record, base_dir=REPO_ROOT)
    assert stats is not None, name
    assert stats["total"]["p99_ms"] is not None
    assert {"decode", "predict", "encode"} <= set(stats["phases"])


def test_committed_record_decomposition_sums_within_ten_percent():
    """ISSUE 17 acceptance: the r08 -> r09 p99 decomposition's per-phase
    rows sum within 10% of the headline p99 delta (exactly, by
    construction — the derived rows close the budget)."""
    stats = []
    for name in ("BENCH_r08.json", "BENCH_r09.json"):
        with open(os.path.join(REPO_ROOT, name)) as fh:
            stats.append(
                attribution.phase_stats_from_record(
                    json.load(fh), base_dir=REPO_ROOT
                )
            )
    decomp = attribution.decompose_stats(stats[0], stats[1], "p99_ms")
    assert decomp is not None
    headline = decomp["headline_delta_ms"]
    assert headline != 0
    row_sum = sum(r["delta_ms"] for r in decomp["rows"])
    assert abs(row_sum - headline) <= 0.10 * abs(headline)
