"""
Tier-1 lint gate: no bare ``except:`` in gordo_tpu/ (scripts/lint_bare_except.py).

A bare except launders every exception — including KeyboardInterrupt and
SystemExit — into one code path, which defeats the transient-vs-permanent
classification the fault-domain layer (util/faults.py) depends on.
"""

import pathlib
import subprocess
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
LINT = REPO_ROOT / "scripts" / "lint_bare_except.py"


def test_no_bare_except_in_gordo_tpu():
    result = subprocess.run(
        [sys.executable, str(LINT), "gordo_tpu"],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, (
        f"bare 'except:' introduced:\n{result.stdout}{result.stderr}"
    )


def test_lint_flags_bare_except(tmp_path):
    bad = tmp_path / "offender.py"
    bad.write_text(
        "try:\n    pass\nexcept:\n    pass\n"
    )
    result = subprocess.run(
        [sys.executable, str(LINT), str(tmp_path)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert result.returncode == 1
    assert "offender.py:3" in result.stdout


def test_lint_accepts_typed_except(tmp_path):
    ok = tmp_path / "fine.py"
    ok.write_text(
        "try:\n    pass\nexcept Exception:\n    pass\n"
        "try:\n    pass\nexcept (ValueError, KeyError) as exc:\n    raise\n"
    )
    result = subprocess.run(
        [sys.executable, str(LINT), str(tmp_path)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stdout
