"""
Tier-1 lint gates.

- No bare ``except:`` in gordo_tpu/ (scripts/lint_bare_except.py): a bare
  except launders every exception — including KeyboardInterrupt and
  SystemExit — into one code path, which defeats the transient-vs-permanent
  classification the fault-domain layer (util/faults.py) depends on.
- Every registered metric carries a ``gordo_`` prefix and non-empty help
  text (scripts/lint_metric_names.py): metric names are a public API for
  dashboards and alerts; help strings are the operator docs at /metrics.
- Every ``GORDO_TPU_*`` env var read in gordo_tpu/ is documented under
  docs/ or README.md (scripts/lint_env_knobs.py): the knob count has
  outgrown anyone's memory, and an undocumented knob is undiscoverable
  at exactly the moment an operator needs it.
- Every ``BENCH_r*.json`` record conforms to the schema-v2 harness
  contract (scripts/lint_bench_record.py): all canonical sections
  present with an explicit status, summary metrics number-or-null —
  the round-4/5 "bench ran, record useless" postmortems made checkable.
- Every ``gordo_*`` metric a generated Grafana dashboard plots exists in
  a metrics catalog (lint_metric_names.py --dashboards): a panel keyed
  on a renamed metric renders empty silently. Plus a tiny-budget fleet
  scrape smoke holding the merged /metrics exposition to the same
  naming bar.
- Every shipped-programs artifact manifest conforms to the build-to-serve
  contract (scripts/lint_artifact_manifest.py): known schema, complete
  host-fingerprint block, well-formed entries, no missing or orphaned
  ``.jaxprog`` files — a drifted manifest fails silently at cold-node
  boot, downgrading to the compile path.
"""

import json
import pathlib
import subprocess
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parents[2]
LINT = REPO_ROOT / "scripts" / "lint_bare_except.py"
METRIC_LINT = REPO_ROOT / "scripts" / "lint_metric_names.py"
KNOB_LINT = REPO_ROOT / "scripts" / "lint_env_knobs.py"
RECORD_LINT = REPO_ROOT / "scripts" / "lint_bench_record.py"
MANIFEST_LINT = REPO_ROOT / "scripts" / "lint_artifact_manifest.py"
SCENARIO_LINT = REPO_ROOT / "scripts" / "lint_chaos_scenario.py"


def test_no_bare_except_in_gordo_tpu():
    result = subprocess.run(
        [sys.executable, str(LINT), "gordo_tpu"],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, (
        f"bare 'except:' introduced:\n{result.stdout}{result.stderr}"
    )


def test_lint_flags_bare_except(tmp_path):
    bad = tmp_path / "offender.py"
    bad.write_text(
        "try:\n    pass\nexcept:\n    pass\n"
    )
    result = subprocess.run(
        [sys.executable, str(LINT), str(tmp_path)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert result.returncode == 1
    assert "offender.py:3" in result.stdout


def test_lint_accepts_typed_except(tmp_path):
    ok = tmp_path / "fine.py"
    ok.write_text(
        "try:\n    pass\nexcept Exception:\n    pass\n"
        "try:\n    pass\nexcept (ValueError, KeyError) as exc:\n    raise\n"
    )
    result = subprocess.run(
        [sys.executable, str(LINT), str(tmp_path)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, result.stdout


# ------------------------------------------------------ metric-name lint
def _run_metric_lint(root):
    return subprocess.run(
        [sys.executable, str(METRIC_LINT), str(root)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )


def test_no_bad_metric_names_in_gordo_tpu():
    result = _run_metric_lint("gordo_tpu")
    assert result.returncode == 0, (
        f"bad metric registration introduced:\n{result.stdout}{result.stderr}"
    )


def test_metric_lint_flags_missing_prefix_and_help(tmp_path):
    bad = tmp_path / "offender.py"
    bad.write_text(
        "from prometheus_client import Counter, Histogram\n"
        'c = Counter("requests_total", "has help but no prefix")\n'
        'h = Histogram("gordo_good_name_seconds", "")\n'
        "from gordo_tpu.observability import telemetry\n"
        'g = telemetry.gauge("gordo_no_help_at_all")\n'
    )
    result = _run_metric_lint(tmp_path)
    assert result.returncode == 1
    assert "offender.py:2" in result.stdout and "prefix" in result.stdout
    assert "offender.py:3" in result.stdout and "help" in result.stdout
    assert "offender.py:5" in result.stdout


def test_metric_lint_accepts_prefixed_documented_metrics(tmp_path):
    ok = tmp_path / "fine.py"
    ok.write_text(
        "from prometheus_client import Counter\n"
        'c = Counter("gordo_things_total", "things that happened", ["kind"])\n'
        "from gordo_tpu.observability import telemetry\n"
        'h = telemetry.histogram(\n'
        '    name="gordo_thing_seconds", help="how long things took"\n'
        ")\n"
        "# variable names are unlintable and skipped (registry internals)\n"
        "name = 'dynamic'\n"
        "import collections\n"
        "counts = collections.Counter([1, 2, 2])\n"
    )
    result = _run_metric_lint(tmp_path)
    assert result.returncode == 0, result.stdout


# ------------------------------------------------------- env-knob lint
def _run_knob_lint(*args):
    return subprocess.run(
        [sys.executable, str(KNOB_LINT), *map(str, args)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )


def test_every_env_knob_in_gordo_tpu_is_documented():
    result = _run_knob_lint()  # defaults: gordo_tpu vs docs/ + README.md
    assert result.returncode == 0, (
        f"undocumented GORDO_TPU_* knob introduced:\n"
        f"{result.stdout}{result.stderr}"
    )


def test_knob_lint_flags_undocumented_knob(tmp_path):
    src = tmp_path / "src"
    docs = tmp_path / "docs"
    src.mkdir(), docs.mkdir()
    (src / "mod.py").write_text(
        'import os\n'
        'a = os.environ.get("GORDO_TPU_DOCUMENTED_KNOB")\n'
        'b = os.environ.get("GORDO_TPU_SECRET_KNOB")\n'
        '# constructed prefixes are skipped, expansions must be named:\n'
        'c = os.environ.get(f"GORDO_TPU_DYNAMIC_{a}")\n'
    )
    (docs / "page.md").write_text(
        "| `GORDO_TPU_DOCUMENTED_KNOB` | does things |\n"
    )
    result = _run_knob_lint(src, docs)
    assert result.returncode == 1
    assert "GORDO_TPU_SECRET_KNOB" in result.stdout
    assert "GORDO_TPU_DOCUMENTED_KNOB" not in result.stdout
    assert "GORDO_TPU_DYNAMIC_" not in result.stdout


def test_knob_lint_accepts_fully_documented_tree(tmp_path):
    src = tmp_path / "src"
    docs = tmp_path / "docs"
    src.mkdir(), docs.mkdir()
    (src / "mod.py").write_text(
        'import os\nx = os.environ.get("GORDO_TPU_FINE_KNOB")\n'
    )
    (docs / "page.md").write_text("`GORDO_TPU_FINE_KNOB` turns it on\n")
    result = _run_knob_lint(src, docs)
    assert result.returncode == 0, result.stdout


def test_metric_lint_flags_unbounded_label_cardinality(tmp_path):
    bad = tmp_path / "offender.py"
    bad.write_text(
        "from gordo_tpu.observability import telemetry\n"
        '# a bounded identity label (model names) is fine\n'
        'ok = telemetry.counter(\n'
        '    "gordo_fine_total", "per-model events", ("model",)\n'
        ")\n"
        '# per-request identity is a cardinality bomb\n'
        'bad = telemetry.counter(\n'
        '    "gordo_bomb_total", "per-trace events", ("trace_id",)\n'
        ")\n"
    )
    result = _run_metric_lint(tmp_path)
    assert result.returncode == 1
    assert "trace_id" in result.stdout and "unbounded" in result.stdout
    assert "gordo_fine_total" not in result.stdout


def test_metric_lint_catalog_coverage(tmp_path):
    """--catalog: every catalog metric must appear in a doc or dashboard."""
    catalog = tmp_path / "metrics.py"
    catalog.write_text(
        "from gordo_tpu.observability import telemetry\n"
        'a = telemetry.counter("gordo_plotted_total", "shown somewhere")\n'
        'b = telemetry.counter("gordo_orphan_total", "shown nowhere")\n'
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "page.md").write_text("`gordo_plotted_total` counts things\n")
    result = subprocess.run(
        [
            sys.executable, str(METRIC_LINT), str(tmp_path),
            "--catalog", str(catalog), "--refs", str(docs),
        ],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert result.returncode == 1
    assert "gordo_orphan_total" in result.stdout
    assert "gordo_plotted_total" not in result.stdout


# -------------------------------------------------- bench-record lint
def _run_record_lint(*args):
    return subprocess.run(
        [sys.executable, str(RECORD_LINT), *map(str, args)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )


def _write_record(tmp_path, name, parsed):
    path = tmp_path / name
    path.write_text(json.dumps({"n": 99, "rc": 0, "parsed": parsed}))
    return path


def _valid_parsed():
    if str(REPO_ROOT) not in sys.path:
        sys.path.insert(0, str(REPO_ROOT))
    import bench

    return {
        "schema_version": bench.RECORD_SCHEMA_VERSION,
        "metric": "m",
        "unit": "machines/min",
        "platform": "cpu",
        "value": 123.0,
        "server_samples_per_sec": None,
        "sections": {name: "completed" for name in bench.SECTION_NAMES},
    }


def test_bench_record_lint_checked_in_records_pass():
    """The default invocation (what tier-1 runs): every checked-in record
    is valid or legacy — a future round committing a malformed record
    fails the suite."""
    result = _run_record_lint()
    assert result.returncode == 0, result.stdout + result.stderr


def test_bench_record_lint_accepts_valid_schema_v2(tmp_path):
    good = _write_record(tmp_path, "BENCH_r90.json", _valid_parsed())
    result = _run_record_lint(good)
    assert result.returncode == 0, result.stdout + result.stderr


def test_bench_record_lint_flags_unaccounted_section(tmp_path):
    parsed = _valid_parsed()
    del parsed["sections"]["windowed"]
    bad = _write_record(tmp_path, "BENCH_r91.json", parsed)
    result = _run_record_lint(bad)
    assert result.returncode == 1
    assert "windowed" in result.stdout and "unaccounted" in result.stdout


def test_bench_record_lint_flags_unknown_status_and_bad_types(tmp_path):
    parsed = _valid_parsed()
    parsed["sections"]["headline"] = "exploded"  # not in the vocabulary
    parsed["value"] = "fast"  # not number-or-null
    bad = _write_record(tmp_path, "BENCH_r92.json", parsed)
    result = _run_record_lint(bad)
    assert result.returncode == 1
    assert "exploded" in result.stdout
    assert "parsed.value" in result.stdout


def test_bench_record_lint_legacy_skip_and_strict(tmp_path):
    """Pre-schema records (r01–r05 shape, parsed without schema_version or
    even parsed: null) are skipped by default and rejected by --strict."""
    legacy = _write_record(tmp_path, "BENCH_r01.json", {"value": 1.0})
    lost = tmp_path / "BENCH_r04.json"
    lost.write_text(json.dumps({"n": 4, "rc": 124, "parsed": None}))
    assert _run_record_lint(legacy, lost).returncode == 0
    result = _run_record_lint("--strict", legacy, lost)
    assert result.returncode == 1
    assert "legacy" in result.stdout


def test_metric_lint_default_invocation_checks_real_catalog():
    """The bare invocation (what tier-1 runs) includes catalog coverage
    of observability/metrics.py against docs + dashboards AND the reverse
    dashboard-grounding check over resources/grafana/dashboards."""
    result = subprocess.run(
        [sys.executable, str(METRIC_LINT)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )
    assert result.returncode == 0, (
        f"metric catalog drifted from docs/dashboards:\n"
        f"{result.stdout}{result.stderr}"
    )


# ------------------------------------------------ dashboard grounding
def _dashboard_fixture(tmp_path, exprs):
    """A minimal dashboard JSON + a catalog registering two metrics."""
    catalog = tmp_path / "catalog.py"
    catalog.write_text(
        "from gordo_tpu.observability import telemetry\n"
        'a = telemetry.counter("gordo_real_total", "a real counter")\n'
        'b = telemetry.histogram("gordo_real_seconds", "a real histogram")\n'
    )
    dashboards = tmp_path / "dashboards"
    dashboards.mkdir()
    (dashboards / "dash.json").write_text(json.dumps({
        "panels": [
            {"targets": [{"expr": expr} for expr in exprs]},
        ],
    }))
    return dashboards, catalog


def _run_dashboard_lint(tmp_path, dashboards, catalog):
    # an explicit (empty-of-offenders) root keeps the default-tree catalog
    # checks out of the way; only the dashboard grounding is under test
    return subprocess.run(
        [
            sys.executable, str(METRIC_LINT), str(tmp_path / "dashboards"),
            "--dashboards", str(dashboards),
            "--dashboard-catalogs", str(catalog),
        ],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )


def test_dashboard_lint_flags_uncataloged_metric(tmp_path):
    dashboards, catalog = _dashboard_fixture(tmp_path, [
        'rate(gordo_real_total[5m])',
        'sum(rate(gordo_ghost_total[5m]))',  # nothing registers this
    ])
    result = _run_dashboard_lint(tmp_path, dashboards, catalog)
    assert result.returncode == 1
    assert "gordo_ghost_total" in result.stdout
    assert "render empty" in result.stdout
    assert "gordo_real_total" not in result.stdout


def test_dashboard_lint_accepts_cataloged_and_label_positions(tmp_path):
    dashboards, catalog = _dashboard_fixture(tmp_path, [
        # histogram suffixes resolve to the base family; gordo_*-shaped
        # tokens in label positions (selector bodies, by-clauses) are
        # labels, not metric references
        'histogram_quantile(0.99, sum by (le, gordo_name) '
        '(rate(gordo_real_seconds_bucket{gordo_name="m"}[5m])))',
        'sum(gordo_real_total{gordo_project=~"$project"})',
    ])
    result = _run_dashboard_lint(tmp_path, dashboards, catalog)
    assert result.returncode == 0, result.stdout + result.stderr


def test_dashboard_lint_grounds_gateway_family(tmp_path):
    """The gateway dashboard's ``gordo_gateway_*`` exprs are grounded by
    the real catalog — and the reverse check is non-vacuous: against a
    catalog without the gateway registrations, every panel is flagged."""
    dashboards = tmp_path / "dashboards"
    dashboards.mkdir()
    source = (
        REPO_ROOT / "resources" / "grafana" / "dashboards"
        / "gordo_tpu_gateway.json"
    )
    (dashboards / "gordo_tpu_gateway.json").write_text(source.read_text())

    real_catalog = REPO_ROOT / "gordo_tpu" / "observability" / "metrics.py"
    result = _run_dashboard_lint(tmp_path, dashboards, real_catalog)
    assert result.returncode == 0, result.stdout + result.stderr

    gateway_free = tmp_path / "catalog.py"
    gateway_free.write_text(
        "from gordo_tpu.observability import telemetry\n"
        'a = telemetry.counter("gordo_real_total", "a real counter")\n'
    )
    result = _run_dashboard_lint(tmp_path, dashboards, gateway_free)
    assert result.returncode == 1
    assert "gordo_gateway_requests_total" in result.stdout
    assert "gordo_gateway_proxy_seconds" in result.stdout


# ------------------------------------------------ exemplar discipline
def _run_exemplar_lint(tmp_path, exposition_text):
    exposition = tmp_path / "metrics.txt"
    exposition.write_text(exposition_text)
    empty_root = tmp_path / "empty"
    empty_root.mkdir(exist_ok=True)
    # an explicit empty root keeps the default-tree checks out of the way;
    # only the exemplar discipline is under test
    return subprocess.run(
        [
            sys.executable, str(METRIC_LINT), str(empty_root),
            "--exposition", str(exposition),
        ],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )


def test_exemplar_lint_accepts_real_renderer_output(tmp_path):
    """The telemetry renderer's own exemplar exposition is the reference:
    trace_id-only labels, bucket lines only, under the per-family cap."""
    from gordo_tpu.observability import telemetry, tracing

    registry = telemetry.MetricsRegistry()
    hist = registry.histogram(
        "gordo_exemplar_demo_seconds", "demo", buckets=(0.01, 0.1, 1.0)
    )
    for value in (0.005, 0.05, 0.5):
        with tracing.request_root():
            hist.observe(value)
    text = registry.render_text()
    assert " # {" in text, "renderer stopped emitting exemplars"
    result = _run_exemplar_lint(tmp_path, text)
    assert result.returncode == 0, result.stdout + result.stderr


def test_exemplar_lint_flags_foreign_labels(tmp_path):
    result = _run_exemplar_lint(
        tmp_path,
        'gordo_x_seconds_bucket{le="1"} 3 # {trace_id="a",user="bob"} '
        "0.5 1.0\n"
        'gordo_x_seconds_bucket{le="2"} 3 # {span_id="a"} 0.5 1.0\n',
    )
    assert result.returncode == 1
    assert "'user'" in result.stdout
    assert "'span_id'" in result.stdout
    assert "only ['trace_id']" in result.stdout


def test_exemplar_lint_flags_non_bucket_and_cap(tmp_path):
    over_cap = "\n".join(
        f'gordo_x_seconds_bucket{{le="{i}"}} 1 # {{trace_id="t{i}"}} 0.5 1.0'
        for i in range(17)
    )
    result = _run_exemplar_lint(
        tmp_path,
        'gordo_x_seconds_sum 1.2 # {trace_id="a"} 0.5 1.0\n' + over_cap,
    )
    assert result.returncode == 1
    assert "non-bucket sample 'gordo_x_seconds_sum'" in result.stdout
    assert "exposes 17 exemplars (cap 16)" in result.stdout


# -------------------------------------------- artifact-manifest lint
def _run_manifest_lint(*args):
    return subprocess.run(
        [sys.executable, str(MANIFEST_LINT), *map(str, args)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )


def _manifest_fixture(tmp_path, mutate=None):
    """A minimal valid artifact with a shipped-programs manifest; `mutate`
    edits the manifest dict (and may touch the dir) before writing."""
    programs_dir = tmp_path / "artifact" / "programs"
    programs_dir.mkdir(parents=True)
    fname = "abc123def456-n128-b1-c8.jaxprog"
    (programs_dir / fname).write_bytes(b"\x80\x04N.")
    manifest = {
        "schema_version": 1,
        "fingerprint": "c94e61e4dfe1",
        "platform": "cpu",
        "machine": "x86_64",
        "cpu_features": ["avx2", "fma"],
        "jaxlib": "0.4.37",
        "programs": [
            {
                "file": fname,
                "spec_key": "abc123def456",
                "n_pad": 128,
                "b_pad": 1,
                "capacity": 8,
                "x_shape": [1, 128, 4],
                "dtype": "float32",
                "compile_s": 0.25,
            }
        ],
    }
    if mutate:
        mutate(manifest, programs_dir)
    (programs_dir / "manifest.json").write_text(json.dumps(manifest))
    return tmp_path / "artifact"


def test_manifest_lint_default_invocation_passes():
    """The bare invocation (what tier-1 runs): build outputs are not
    checked in, so the repo-root scan finds nothing and passes — and a
    future round that DOES commit an artifact gets it linted for free."""
    result = _run_manifest_lint()
    assert result.returncode == 0, result.stdout + result.stderr


def test_manifest_lint_accepts_valid_artifact(tmp_path):
    artifact = _manifest_fixture(tmp_path)
    result = _run_manifest_lint(artifact)
    assert result.returncode == 0, result.stdout + result.stderr
    assert "1 artifact manifest(s) valid" in result.stdout


def test_manifest_lint_flags_missing_fingerprint_and_schema(tmp_path):
    def mutate(manifest, programs_dir):
        manifest["fingerprint"] = ""
        manifest["schema_version"] = 99

    artifact = _manifest_fixture(tmp_path, mutate)
    result = _run_manifest_lint(artifact)
    assert result.returncode == 1
    assert "fingerprint" in result.stdout
    assert "schema_version" in result.stdout


def test_manifest_lint_flags_missing_and_orphaned_files(tmp_path):
    def mutate(manifest, programs_dir):
        # indexed but absent on disk
        manifest["programs"].append(
            {**manifest["programs"][0], "file": "ghost-n128-b4-c8.jaxprog"}
        )
        # on disk but unindexed
        (programs_dir / "orphan-n1024-b1-c8.jaxprog").write_bytes(b"x")

    artifact = _manifest_fixture(tmp_path, mutate)
    result = _run_manifest_lint(artifact)
    assert result.returncode == 1
    assert "ghost-n128-b4-c8.jaxprog" in result.stdout
    assert "does not exist" in result.stdout
    assert "orphan-n1024-b1-c8.jaxprog" in result.stdout
    assert "orphaned" in result.stdout


def test_manifest_lint_real_shipped_artifact_passes(tmp_path, monkeypatch):
    """Ground truth: a manifest written by the REAL build-side shipper
    passes the lint — the lint and programs.ship_programs can't drift
    apart without this failing."""
    pytest = __import__("pytest")
    np = __import__("numpy")
    from gordo_tpu.serializer import programs as programs_mod

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")

    class _Estimator:
        pass

    import jax.numpy as jnp

    from gordo_tpu.models.models import AutoEncoder

    spec = AutoEncoder(kind="feedforward_hourglass").build_spec(4, 4)
    from gordo_tpu.ops.nn import init_model_params

    estimator = _Estimator()
    estimator.spec_ = spec
    estimator.params_ = init_model_params(
        __import__("jax").random.PRNGKey(0), spec
    )
    artifact = tmp_path / "artifact"
    artifact.mkdir()
    (artifact / "metadata.json").write_text(json.dumps({
        "dataset": {"tags": ["a", "b", "c", "d"]},
        "metadata": {"build_metadata": {"model": {"model_offset": 0}}},
    }))
    shipped = programs_mod.ship_programs(
        estimator, str(artifact), expected_fleet=1,
        bucket_rows=(128,), fuse_widths=(1,),
    )
    assert shipped == 1
    result = _run_manifest_lint(artifact)
    assert result.returncode == 0, result.stdout + result.stderr


def test_fleet_scrape_smoke(tmp_path, monkeypatch):
    """Tiny-budget fleet-scrape smoke: flush this process's shard, render
    the merged exposition (the exact bytes a no-prometheus /metrics
    serves), and hold every exposed family to the lint's naming bar."""
    import re

    from gordo_tpu.observability import shared, telemetry

    monkeypatch.setenv(shared.ENV_DIR, str(tmp_path))
    shared.reset_for_tests()
    try:
        telemetry.counter(
            "gordo_server_lint_smoke_total", "scrape-smoke probe"
        ).inc()
        text = shared.render_fleet_text()
        assert "gordo_server_fleet_workers 1" in text
        assert "gordo_server_lint_smoke_total 1" in text
        for line in text.splitlines():
            if not line or line.startswith("#"):
                continue
            name = re.split(r"[{\s]", line, maxsplit=1)[0]
            assert name.startswith("gordo_"), line
    finally:
        shared.reset_for_tests()


# ---------------------------------------------------- chaos-scenario lint
def _run_scenario_lint(*paths):
    return subprocess.run(
        [sys.executable, str(SCENARIO_LINT), *map(str, paths)],
        cwd=str(REPO_ROOT),
        capture_output=True,
        text=True,
    )


def test_chaos_scenario_lint_committed_scenarios_pass():
    """The bare invocation (what tier-1 runs): every scenario under
    resources/chaos/ parses against the conductor's live vocabulary."""
    result = _run_scenario_lint()
    assert result.returncode == 0, result.stdout + result.stderr


def test_chaos_scenario_lint_flags_bad_vocabulary(tmp_path):
    bad = tmp_path / "bad_action.yaml"
    bad.write_text(
        "name: bad\n"
        "load:\n  phases:\n    - {shape: flat, qps: 5, duration: 2}\n"
        "timeline:\n  - {at: 1.0, action: reboot_node, node: 0}\n"
        "invariants:\n  - {check: availability, min: 0.9}\n"
    )
    result = _run_scenario_lint(bad)
    assert result.returncode == 1
    assert "reboot_node" in result.stdout

    bad_site = tmp_path / "bad_site.yaml"
    bad_site.write_text(
        "name: bad-site\n"
        "fault_plan:\n  rules:\n    - {site: not_a_site, error: transient}\n"
        "invariants:\n  - {check: availability}\n"
    )
    result = _run_scenario_lint(bad_site)
    assert result.returncode == 1
    assert "not_a_site" in result.stdout


def test_chaos_scenario_lint_flags_structural_problems(tmp_path):
    # no invariants = asserts nothing; late action = never fires
    empty = tmp_path / "no_invariants.yaml"
    empty.write_text(
        "name: hollow\n"
        "load:\n  phases:\n    - {shape: flat, qps: 5, duration: 2}\n"
    )
    late = tmp_path / "late_action.yaml"
    late.write_text(
        "name: late\n"
        "load:\n  phases:\n    - {shape: flat, qps: 5, duration: 2}\n"
        "timeline:\n  - {at: 99.0, action: kill_node, node: 0}\n"
        "invariants:\n  - {check: availability}\n"
    )
    result = _run_scenario_lint(empty, late)
    assert result.returncode == 1
    assert "no invariants" in result.stdout
    assert "fires after the load ends" in result.stdout


def test_chaos_scenario_lint_caps_horizon(tmp_path):
    slow = tmp_path / "marathon.yaml"
    slow.write_text(
        "name: marathon\n"
        "load:\n  phases:\n    - {shape: flat, qps: 5, duration: 600}\n"
        "invariants:\n  - {check: availability}\n"
    )
    result = _run_scenario_lint(slow)
    assert result.returncode == 1
    assert "exceeds" in result.stdout
