"""
Multi-process serving pool: run_server's prefork arbiter as real processes.

The reference delegates worker pooling to gunicorn (server.py:233-297) and
never tests worker death; here the arbiter is ours, so the contract — N
workers accepting on one inherited socket, dead workers reaped and
respawned, traffic surviving a worker SIGKILL — is pinned by this drive.
Runs the server as a subprocess on the CPU backend (the verify recipe's
multi-process drive, automated).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from _nethelpers import free_port as _free_port
from _nethelpers import wait_for as _wait_for

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SERVER_SCRIPT = """
import logging, os, sys
logging.basicConfig(level=logging.INFO, stream=sys.stderr)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from gordo_tpu.server.server import run_server
run_server(host="127.0.0.1", port={port}, workers={workers}, warmup={warmup})
"""


def _get(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def _post_json(url: str, payload: dict, timeout: float = 60.0):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.read()


def _worker_pids(arbiter_pid: int):
    # pgrep -P is portable (procps and BSD alike, unlike ps --ppid)
    proc = subprocess.run(
        ["pgrep", "-P", str(arbiter_pid)], capture_output=True, text=True
    )
    # exit 1 = no children (valid); anything else is a tooling failure that
    # must not masquerade as a pool assertion
    if proc.returncode not in (0, 1):
        raise RuntimeError(f"pgrep failed rc={proc.returncode}: {proc.stderr}")
    return [int(p) for p in proc.stdout.split()]


@pytest.fixture()
def server_pool(model_collection_directory, trained_model_directories, tmp_path):
    yield from _pool(model_collection_directory, tmp_path)


@pytest.fixture()
def server_pool_fastlane(
    model_collection_directory, trained_model_directories, tmp_path
):
    """The same 3-worker prefork pool with the socket fast lane mounted
    (GORDO_TPU_FAST_LANE=1) — every pool guarantee must hold identically."""
    yield from _pool(
        model_collection_directory, tmp_path,
        extra_env={"GORDO_TPU_FAST_LANE": "1"},
    )


@pytest.fixture()
def server_pool_fleet(
    model_collection_directory, trained_model_directories, tmp_path
):
    """3-worker pool with telemetry shards on an operator-provided dir and
    prometheus DISABLED (the default config): /metrics must serve the
    merged fleet exposition with no prometheus_client in the loop."""
    telemetry_dir = tmp_path / "telemetry"
    telemetry_dir.mkdir()
    yield from _pool(
        model_collection_directory, tmp_path,
        extra_env={
            "GORDO_TPU_TELEMETRY_DIR": str(telemetry_dir),
            # flush every request: the scrape assertions below must see
            # the last request's increments without waiting out the
            # 0.25s write throttle
            "GORDO_TPU_TELEMETRY_FLUSH_S": "0",
            "GORDO_TPU_DEBUG_ENDPOINTS": "1",
        },
    )


def _pool(model_collection_directory, tmp_path, extra_env=None):
    port = _free_port()
    env = {
        "PATH": os.environ.get("PATH", ""),
        "HOME": os.environ.get("HOME", "/root"),
        "JAX_PLATFORMS": "cpu",
        "MODEL_COLLECTION_DIR": model_collection_directory,
        "PROJECT": "gordo-test",
    }
    env.update(extra_env or {})
    # stderr to a file, not a PIPE: four processes share the stream and an
    # undrained pipe would block a worker mid-request once it fills
    errlog = tmp_path / "server-stderr.log"
    with open(errlog, "w") as errfh:
        # new session so teardown can killpg the WHOLE pool — SIGKILLing
        # only the arbiter would orphan three live worker processes
        proc = subprocess.Popen(
            [sys.executable, "-c",
             _SERVER_SCRIPT.format(repo=REPO, port=port, workers=3, warmup=True)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=errfh,
            start_new_session=True,
        )
    base = f"http://127.0.0.1:{port}"

    def _teardown(sig=signal.SIGTERM):
        try:
            os.killpg(proc.pid, sig)
        except ProcessLookupError:
            pass
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait(timeout=10)

    deadline = time.monotonic() + 120
    last_err = None
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            # the arbiter may have died abruptly (no finally-block cleanup)
            # with forked workers still alive in its session — reap them
            _teardown(signal.SIGKILL)
            raise RuntimeError(
                f"server exited rc={proc.returncode}: "
                f"{errlog.read_text()[-2000:]}"
            )
        try:
            status, _ = _get(f"{base}/healthcheck", timeout=5)
            if status == 200:
                break
        except (urllib.error.URLError, OSError) as exc:
            last_err = exc
        # sleep on BOTH the not-ready and non-200 paths — a half-up server
        # answering 500s must not be hammered in a tight loop
        time.sleep(0.5)
    else:
        _teardown()
        raise RuntimeError(
            f"server never came up: {last_err}; stderr: "
            f"{errlog.read_text()[-2000:]}"
        )
    yield proc, base, errlog
    _teardown()


def test_pool_serves_and_survives_worker_kill(
    server_pool, gordo_project, gordo_name, X_payload
):
    # the canonical frame + the real wire encoding — shared with the
    # in-process server tests so both suites pin one payload
    from gordo_tpu.server.utils import dataframe_to_dict

    proc, base, errlog = server_pool
    url = f"{base}/gordo/v0/{gordo_project}/{gordo_name}/anomaly/prediction"
    frame = dataframe_to_dict(X_payload)
    payload = {"X": frame, "y": frame}

    # the pool booted with warmup: each worker precompiled its serving
    # programs before accepting (run-server --warmup end-to-end)
    assert "serving warmup:" in errlog.read_text()

    status, body = _post_json(url, payload)
    assert status == 200
    assert json.loads(body)["data"]

    workers = _worker_pids(proc.pid)
    assert len(workers) == 3, f"expected 3 workers, got {workers}"

    os.kill(workers[0], signal.SIGKILL)

    # probe the tooling once OUTSIDE _wait_for: its blanket except would
    # swallow _worker_pids' fail-fast RuntimeError for the full timeout
    _worker_pids(proc.pid)

    # the pool keeps serving while the arbiter reaps and respawns — retried
    # because the killed worker may have held in-flight accepts
    assert _wait_for(
        lambda: _post_json(url, payload, timeout=30)[0] == 200, timeout=60
    ), "pool stopped serving after a worker SIGKILL"

    # the arbiter respawns back to full strength
    assert _wait_for(
        lambda: len(
            [p for p in _worker_pids(proc.pid) if p != workers[0]]
        ) == 3,
        timeout=60,
    ), f"pool never respawned to 3 workers: {_worker_pids(proc.pid)}"


def test_pool_fast_lane_serves_hot_and_fallback_routes(
    server_pool_fastlane, gordo_project, gordo_name, X_payload
):
    """run_server with GORDO_TPU_FAST_LANE=1: the prefork pool mounts the
    socket fast lane on the shared listening socket — hot prediction
    POSTs, WSGI-fallback routes, and worker-kill survival all hold."""
    from gordo_tpu.server.utils import dataframe_to_dict

    proc, base, errlog = server_pool_fastlane
    url = f"{base}/gordo/v0/{gordo_project}/{gordo_name}/anomaly/prediction"
    frame = dataframe_to_dict(X_payload)
    payload = {"X": frame, "y": frame}

    status, body = _post_json(url, payload)
    assert status == 200
    data = json.loads(body)["data"]
    assert "total-anomaly-scaled" in data

    # fallback routes answer through the same port
    status, body = _get(f"{base}/gordo/v0/{gordo_project}/models")
    assert status == 200
    assert gordo_name in json.loads(body)["models"]

    workers = _worker_pids(proc.pid)
    assert len(workers) == 3
    os.kill(workers[0], signal.SIGKILL)
    _worker_pids(proc.pid)
    assert _wait_for(
        lambda: _post_json(url, payload, timeout=30)[0] == 200, timeout=60
    ), "fast-lane pool stopped serving after a worker SIGKILL"


def test_pool_metrics_serve_fleet_sums_without_prometheus(
    server_pool_fleet, gordo_project, gordo_name, X_payload
):
    """ISSUE 9 acceptance drive: a 3-worker prefork pool with
    GORDO_TPU_TELEMETRY_DIR set and prometheus disabled answers /metrics
    with the FLEET-SUMMED counters and merged histograms — whichever
    worker takes the scrape, the prediction total equals the requests
    actually sent, and /debug/slo reports the merged per-model burn
    rates."""
    import re

    from gordo_tpu.server.utils import dataframe_to_dict

    proc, base, errlog = server_pool_fleet
    url = f"{base}/gordo/v0/{gordo_project}/{gordo_name}/anomaly/prediction"
    frame = dataframe_to_dict(X_payload)
    payload = {"X": frame, "y": frame}

    n_requests = 4
    for _ in range(n_requests):
        status, _body = _post_json(url, payload)
        assert status == 200

    series_re = re.compile(
        r"^gordo_server_fleet_requests_total\{([^}]*)\}\s+([0-9.eE+-]+)$",
        re.MULTILINE,
    )
    count_re = re.compile(
        r"^gordo_server_fleet_request_seconds_count\{([^}]*)\}"
        r"\s+([0-9.eE+-]+)$",
        re.MULTILINE,
    )

    def _prediction_sum(pattern, text):
        # sum across workers AND status/endpoint series: the scrape may be
        # answered by any worker, but the merge must account for every
        # prediction the pool served regardless of which worker took it
        return sum(
            float(value)
            for labels, value in pattern.findall(text)
            if "prediction" in labels
        )

    def _scrape():
        status, body = _get(f"{base}/metrics", timeout=10)
        assert status == 200
        return body.decode()

    # the observability feed runs as the response goes out; poll the scrape
    # until every prediction has landed in some worker's shard
    assert _wait_for(
        lambda: _prediction_sum(series_re, _scrape()) >= n_requests,
        timeout=30,
    ), f"fleet counter never reached {n_requests}: {_scrape()[:2000]}"

    text = _scrape()
    # dependency-free Prometheus exposition, not prometheus_client output
    assert "# TYPE gordo_server_fleet_requests_total counter" in text
    assert "# TYPE gordo_server_fleet_workers gauge" in text
    assert _prediction_sum(series_re, text) == n_requests
    # merged histogram: element-wise sum across shards — the prediction
    # count equals the counter total even when workers split the traffic
    assert _prediction_sum(count_re, text) == n_requests
    workers_match = re.search(
        r"^gordo_server_fleet_workers\s+([0-9.]+)$", text, re.MULTILINE
    )
    assert workers_match, text[:2000]
    assert 1 <= float(workers_match.group(1)) <= 3

    # /debug/slo: the merged per-model view over the same shards
    status, body = _get(f"{base}/debug/slo", timeout=10)
    assert status == 200
    fleet = json.loads(body)["fleet"]
    window = fleet["models"][gordo_name]["5m"]
    assert window["requests"] == n_requests
    assert window["errors"] == 0
    assert window["p99_ms"] is not None
    assert window["error_burn_rate"] == 0.0
    assert window["latency_burn_rate"] is not None


def test_boot_failure_during_slow_warmup_trips_throttle(tmp_path):
    """A worker that dies DURING warmup — after more than the fast-death
    wall-clock threshold — must still count as a boot failure (readiness
    pipe, not just wall-clock): before the readiness signal existed, slow
    boot deaths reset the throttle and the arbiter crash-looped forever."""
    # a collection whose model "artifact" kills the process ~2.5s into
    # unpickling — an OOM-kill/abort stand-in the worker cannot catch
    mdir = tmp_path / "boom"
    mdir.mkdir()
    (mdir / "metadata.json").write_text(
        json.dumps({"dataset": {"tags": ["t-0", "t-1"]},
                    "metadata": {"build_metadata": {"model": {"model_offset": 0}}}})
    )
    # hand-written pickle opcodes: GLOBAL exec, TUPLE1 of the source, REDUCE
    payload = (
        b"c__builtin__\nexec\n"
        b"(Vimport time,os; time.sleep(2.5); os._exit(7)\ntR."
    )
    (mdir / "model.pkl").write_bytes(payload)

    port = _free_port()
    env = {
        "PATH": os.environ.get("PATH", ""),
        "HOME": os.environ.get("HOME", "/root"),
        "JAX_PLATFORMS": "cpu",
        "MODEL_COLLECTION_DIR": str(tmp_path),
        "PROJECT": "gordo-test",
    }
    errlog = tmp_path / "stderr.log"
    with open(errlog, "w") as errfh:
        # workers=2 engages the prefork arbiter (workers=1 serves
        # inline); still only ~6 boot-death cycles to the throttle
        proc = subprocess.Popen(
            [sys.executable, "-c",
             _SERVER_SCRIPT.format(repo=REPO, port=port, workers=2, warmup=True)],
            env=env, stdout=subprocess.DEVNULL, stderr=errfh,
            start_new_session=True,
        )
    try:
        # ~6 boot-death cycles, each paying a fresh jax import (~20s on a
        # loaded 1-core host) before the ~2.5s crash; without the
        # readiness classification this NEVER exits (each death looks
        # like a runtime death and resets the throttle)
        rc = proc.wait(timeout=420)
        assert rc != 0
        assert "boot" in errlog.read_text()
    finally:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass


def test_arbiter_drain_on_sigterm_finishes_inflight(
    model_collection_directory, trained_model_directories, tmp_path,
    gordo_project, gordo_name, X_payload,
):
    """Graceful drain (PR 3): SIGTERM to the arbiter forwards TERM to the
    workers, which stop accepting, FINISH the in-flight request (a fault
    plan wedges it for several seconds), and exit — the whole pool shuts
    down rc=0 and the listener is closed afterwards."""
    import threading

    from gordo_tpu.server.utils import dataframe_to_dict

    port = _free_port()
    env = {
        "PATH": os.environ.get("PATH", ""),
        "HOME": os.environ.get("HOME", "/root"),
        "JAX_PLATFORMS": "cpu",
        "MODEL_COLLECTION_DIR": model_collection_directory,
        "PROJECT": "gordo-test",
        # hold the in-flight request inside the handler long enough that
        # SIGTERM provably lands mid-request (first predict only)
        "GORDO_TPU_FAULT_PLAN": json.dumps(
            {"rules": [{"site": "serve_predict", "times": 1,
                        "error": "wedge", "seconds": 6}]}
        ),
        # the wedged request also pays its first-predict compile; the
        # drain budget must outlast it on a loaded CPU host
        "GORDO_TPU_DRAIN_S": "180",
    }
    errlog = tmp_path / "drain-stderr.log"
    with open(errlog, "w") as errfh:
        proc = subprocess.Popen(
            [sys.executable, "-c",
             _SERVER_SCRIPT.format(repo=REPO, port=port, workers=2,
                                   warmup=False)],
            env=env, stdout=subprocess.DEVNULL, stderr=errfh,
            start_new_session=True,
        )
    base = f"http://127.0.0.1:{port}"
    try:
        assert _wait_for(
            lambda: _get(f"{base}/healthcheck", timeout=5)[0] == 200,
            timeout=120,
        ), f"pool never came up: {errlog.read_text()[-2000:]}"

        url = (
            f"{base}/gordo/v0/{gordo_project}/{gordo_name}"
            f"/anomaly/prediction"
        )
        frame = dataframe_to_dict(X_payload)
        result = {}

        def inflight():
            try:
                result["resp"] = _post_json(
                    url, {"X": frame, "y": frame}, timeout=240
                )
            except BaseException as exc:  # noqa: BLE001
                result["error"] = exc

        t = threading.Thread(target=inflight)
        t.start()
        time.sleep(2.0)  # the request is wedged inside a worker
        assert proc.poll() is None
        os.kill(proc.pid, signal.SIGTERM)  # the ARBITER only

        t.join(timeout=240)
        assert not t.is_alive(), "in-flight request never completed"
        assert "error" not in result, (
            f"in-flight request cut during drain: {result['error']!r}; "
            f"stderr: {errlog.read_text()[-2000:]}"
        )
        status, body = result["resp"]
        assert status == 200
        assert json.loads(body)["data"]

        # the whole pool exits cleanly within the drain budget
        rc = proc.wait(timeout=240)
        assert rc == 0, f"stderr: {errlog.read_text()[-2000:]}"
        assert "draining" in errlog.read_text()

        # listener closed: nothing accepts on the port anymore
        with pytest.raises((urllib.error.URLError, OSError)):
            _get(f"{base}/healthcheck", timeout=5)
    finally:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
