import numpy as np
import pandas as pd
import pytest
import yaml

from gordo_tpu.parallel import BatchedModelBuilder, default_mesh
from gordo_tpu.workflow.normalized_config import NormalizedConfig


def _machine_block(name, n_tags=4, epochs=1, model=None):
    tags = "".join(f"\n      - {name}-tag-{j}" for j in range(n_tags))
    model = model or f"""
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        require_thresholds: true
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
            - sklearn.preprocessing.MinMaxScaler
            - gordo_tpu.models.models.AutoEncoder:
                kind: feedforward_hourglass
                epochs: {epochs}"""
    return f"""
  - name: {name}
    dataset:
      tags:{tags}
      train_start_date: '2019-01-01T00:00:00+00:00'
      train_end_date: '2019-01-03T00:00:00+00:00'
      data_provider: {{type: RandomDataProvider}}
    model:{model}
"""


def _machines(config_yaml):
    return NormalizedConfig(yaml.safe_load(config_yaml), project_name="pp").machines


def test_mesh_has_8_virtual_devices():
    mesh = default_mesh()
    assert int(np.prod(list(mesh.shape.values()))) == 8


@pytest.fixture(scope="module")
def batch_results():
    cfg = "machines:" + "".join(_machine_block(f"bm-{i}") for i in range(3))
    machines = _machines(cfg)
    return machines, BatchedModelBuilder(machines).build()


def test_batched_build_returns_in_order(batch_results):
    machines, results = batch_results
    assert len(results) == 3
    for machine, (model, machine_out) in zip(machines, results):
        assert machine_out.name == machine.name


def test_batched_artifacts_match_serial_api(batch_results):
    _, results = batch_results
    model, machine_out = results[0]
    md = machine_out.to_dict()["metadata"]["build_metadata"]["model"]
    # same metadata surface as the serial ModelBuilder
    assert md["model_offset"] == 0
    assert "aggregate-threshold" in md["model_meta"]
    assert "feature-thresholds" in md["model_meta"]
    scores = md["cross_validation"]["scores"]
    assert "r2-score" in scores
    assert {"fold-mean", "fold-std", "fold-1", "fold-2", "fold-3"} <= set(
        scores["r2-score"]
    )
    splits = md["cross_validation"]["splits"]
    assert "fold-1-train-start" in splits
    for entry in scores.values():
        assert all(np.isfinite(v) for v in entry.values())


def test_batched_model_scores_anomalies(batch_results):
    machines, results = batch_results
    model, _ = results[1]
    cols = [t.name for t in machines[1].dataset.tag_list]
    idx = pd.date_range("2020-01-01", periods=20, freq="10min", tz="UTC")
    X = pd.DataFrame(np.random.rand(20, 4), columns=cols, index=idx)
    frame = model.anomaly(X, X, frequency=pd.Timedelta("10min"))
    assert "total-anomaly-confidence" in frame.columns.get_level_values(0)
    assert len(frame) == 20


def _kfcv_block(name, n_tags=4, window=12):
    return _machine_block(
        name,
        n_tags=n_tags,
        model=f"""
      gordo_tpu.models.anomaly.diff.DiffBasedKFCVAnomalyDetector:
        require_thresholds: true
        window: {window}
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
            - sklearn.preprocessing.MinMaxScaler
            - gordo_tpu.models.models.AutoEncoder:
                kind: feedforward_hourglass
                epochs: 1""",
    )


def test_kfcv_machines_take_batched_path():
    from gordo_tpu.parallel.batch_trainer import _plan_machine

    machines = _machines("machines:" + _kfcv_block("kf-0"))
    plan = _plan_machine(machines[0])
    assert plan is not None and plan.kfcv


def test_kfcv_batched_build_end_to_end():
    from gordo_tpu.models.anomaly.diff import DiffBasedKFCVAnomalyDetector

    cfg = "machines:" + _kfcv_block("kf-a") + _kfcv_block("kf-b")
    machines = _machines(cfg)
    results = BatchedModelBuilder(machines, serial_fallback=False).build()
    assert len(results) == 2
    for model, machine_out in results:
        assert isinstance(model, DiffBasedKFCVAnomalyDetector)
        assert np.isfinite(model.aggregate_threshold_)
        assert np.isfinite(model.feature_thresholds_).all()
        md = machine_out.to_dict()["metadata"]["build_metadata"]["model"]
        assert "aggregate-threshold" in md["model_meta"]
    model, _ = results[0]
    cols = [t.name for t in machines[0].dataset.tag_list]
    idx = pd.date_range("2020-01-01", periods=30, freq="10min", tz="UTC")
    X = pd.DataFrame(np.random.rand(30, 4), columns=cols, index=idx)
    frame = model.anomaly(X, X, frequency=pd.Timedelta("10min"))
    assert "total-anomaly-confidence" in frame.columns.get_level_values(0)


def test_kfcv_threshold_math_matches_serial():
    """_set_kfcv_thresholds must reproduce the serial KFCV detector's
    percentile thresholds exactly, given the same fold predictions (here
    from a deterministic LinearRegression base estimator)."""
    from types import SimpleNamespace

    from sklearn.linear_model import LinearRegression
    from sklearn.model_selection import TimeSeriesSplit
    from sklearn.preprocessing import MinMaxScaler

    from gordo_tpu.models.anomaly.diff import DiffBasedKFCVAnomalyDetector

    rng = np.random.RandomState(7)
    X = rng.rand(300, 4)
    y = X @ rng.rand(4, 4) + 0.01 * rng.rand(300, 4)

    serial = DiffBasedKFCVAnomalyDetector(
        base_estimator=LinearRegression(),
        scaler=MinMaxScaler(),
        window=24,
        shuffle=False,
    )
    serial.cross_validate(
        X=pd.DataFrame(X), y=pd.DataFrame(y), cv=TimeSeriesSplit(n_splits=3)
    )

    # batched-side replication from per-fold predictions
    bounds, fold_preds = [], []
    for train_idx, test_idx in TimeSeriesSplit(n_splits=3).split(X):
        tr_end = int(train_idx[-1]) + 1
        te_start, te_end = int(test_idx[0]), int(test_idx[-1]) + 1
        bounds.append((tr_end, te_start, te_end))
        lr = LinearRegression().fit(X[:tr_end], y[:tr_end])
        fold_preds.append(lr.predict(X[te_start:te_end]))

    batched = DiffBasedKFCVAnomalyDetector(
        base_estimator=LinearRegression(),
        scaler=MinMaxScaler(),
        window=24,
        shuffle=False,
    )
    BatchedModelBuilder._set_kfcv_thresholds(
        None, batched, SimpleNamespace(y=y), fold_preds, bounds
    )
    np.testing.assert_allclose(
        batched.aggregate_threshold_, serial.aggregate_threshold_, rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(batched.feature_thresholds_),
        np.asarray(serial.feature_thresholds_),
        rtol=1e-9,
    )


def test_heterogeneous_buckets_and_fallback():
    cfg = "machines:" + (
        _machine_block("small-0", n_tags=2)
        + _machine_block("small-1", n_tags=2)
        + _machine_block("wide-0", n_tags=6)
        + _machine_block(
            "plain-sklearn",
            n_tags=2,
            model="""
      sklearn.pipeline.Pipeline:
        steps:
        - sklearn.preprocessing.MinMaxScaler
        - sklearn.linear_model.LinearRegression
""",
        )
    )
    machines = _machines(cfg)
    results = BatchedModelBuilder(machines).build()
    assert len(results) == 4
    # 2-tag and 6-tag machines end up in different buckets but both train
    m_small, _ = results[0]
    m_wide, _ = results[2]
    assert m_small.base_estimator.steps[1][1].spec_.n_features == 2
    assert m_wide.base_estimator.steps[1][1].spec_.n_features == 6
    # sklearn model went through the serial fallback and is fitted
    m_sk, machine_sk = results[3]
    X = np.random.rand(5, 2)
    assert m_sk.predict(X).shape[0] == 5


def test_batched_seed_determinism():
    cfg = "machines:" + _machine_block("det-0")
    machines1 = _machines(cfg)
    r1 = BatchedModelBuilder(machines1).build()
    machines2 = _machines(cfg)
    r2 = BatchedModelBuilder(machines2).build()
    cols = [t.name for t in machines1[0].dataset.tag_list]
    X = pd.DataFrame(np.random.RandomState(0).rand(16, 4), columns=cols)
    out1 = r1[0][0].predict(X)
    out2 = r2[0][0].predict(X)
    assert np.allclose(out1, out2)


def test_serial_fallback_disabled_raises():
    cfg = "machines:" + _machine_block(
        "nofall",
        n_tags=2,
        model="""
      sklearn.pipeline.Pipeline:
        steps:
        - sklearn.preprocessing.MinMaxScaler
        - sklearn.linear_model.LinearRegression
""",
    )
    machines = _machines(cfg)
    with pytest.raises(ValueError):
        BatchedModelBuilder(machines, serial_fallback=False).build()


def test_seed_independent_of_bucket_composition():
    """A machine's weights must not depend on which machines share its bucket."""
    solo = _machines("machines:" + _machine_block("indep-a"))
    r_solo = BatchedModelBuilder(solo).build()
    pair = _machines(
        "machines:" + _machine_block("indep-b") + _machine_block("indep-a")
    )
    r_pair = BatchedModelBuilder(pair).build()
    cols = [t.name for t in solo[0].dataset.tag_list]
    X = pd.DataFrame(np.random.RandomState(1).rand(16, 4), columns=cols)
    out_solo = r_solo[0][0].predict(X)
    out_pair = r_pair[1][0].predict(X)  # indep-a is second in the pair config
    assert np.allclose(out_solo, out_pair)


def test_cross_val_only_goes_serial():
    cfg = "machines:" + _machine_block("cvonly")
    machines = _machines(cfg)
    machines[0].evaluation["cv_mode"] = "cross_val_only"
    results = BatchedModelBuilder(machines).build()
    model, machine_out = results[0]
    # serial cross_val_only contract: inner estimator not fitted
    ae = model.base_estimator.steps[-1][1]
    assert not hasattr(ae, "params_")
    assert machine_out.metadata.build_metadata.model.cross_validation.scores


def test_unsupported_metric_goes_serial():
    cfg = "machines:" + _machine_block("oddmetric")
    machines = _machines(cfg)
    machines[0].evaluation["metrics"] = ["sklearn.metrics.max_error"]
    results = BatchedModelBuilder(machines).build()
    _, machine_out = results[0]
    scores = machine_out.metadata.build_metadata.model.cross_validation.scores
    assert any("max-error" in k for k in scores)


@pytest.mark.parametrize("force_numpy", [False, True])
def test_rolling_min_max_matches_pandas(force_numpy, monkeypatch):
    """The threshold math (native kernel and numpy fallback) must equal
    pandas rolling(w).min().max() — including NaN inputs, where a window
    containing NaN has NaN min and the final max skips NaN windows."""
    if force_numpy:
        from gordo_tpu import native

        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_load_failed", True)
    rng = np.random.RandomState(7)
    for n, w in [(200, 6), (144, 144), (50, 6), (5, 6), (6, 6)]:
        for nan_frac in (0.0, 0.15, 1.0):
            series = rng.rand(n)
            if nan_frac:
                series[rng.rand(n) < nan_frac] = np.nan
            expected = pd.Series(series).rolling(w).min().max()
            got = BatchedModelBuilder._rolling_min_max(series, w)
            if np.isnan(expected):
                assert np.isnan(got), (n, w, nan_frac)
            else:
                assert np.isclose(got, expected), (n, w, nan_frac)

            frame = rng.rand(n, 4)
            if nan_frac:
                frame[rng.rand(n, 4) < nan_frac] = np.nan
            expected_df = pd.DataFrame(frame).rolling(w).min().max()
            got_df = BatchedModelBuilder._rolling_min_max(frame, w)
            assert np.allclose(
                np.asarray(got_df), expected_df.to_numpy(), equal_nan=True
            ), (n, w, nan_frac)


def test_chunked_build_matches_unchunked():
    """Chunking is an execution detail: results must be identical for any
    chunk size (same seeds, same data)."""
    import jax

    cfg = "machines:" + "".join(_machine_block(f"ck-{i}") for i in range(3))
    small = BatchedModelBuilder(_machines(cfg), chunk_size=1).build()
    big = BatchedModelBuilder(_machines(cfg), chunk_size=64).build()
    for (m_small, _), (m_big, _) in zip(small, big):
        a = m_small.base_estimator.steps[-1][1].params_
        b = m_big.base_estimator.steps[-1][1].params_
        for la, lb in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
            assert np.allclose(np.asarray(la), np.asarray(lb))
    # thresholds identical too (assembly independent of chunking)
    assert np.isclose(
        small[0][0].aggregate_threshold_, big[0][0].aggregate_threshold_
    )


def _cache_marker(machine_out):
    return (machine_out.metadata.user_defined or {}).get(
        "build-metadata", {}
    ) == {"from_cache": True}


def test_fleet_checkpoint_resume(tmp_path):
    """A fleet build with output/register dirs persists each machine as it
    finishes; a rerun loads everything from cache, and wiping one machine's
    cache entry retrains only that machine."""
    import os
    import shutil

    from gordo_tpu.builder.build_model import ModelBuilder
    from gordo_tpu.util import disk_registry

    config = "machines:" + "".join(_machine_block(f"ckpt-{i}") for i in range(4))
    out_dir, reg_dir = str(tmp_path / "out"), str(tmp_path / "reg")

    machines = _machines(config)
    first = BatchedModelBuilder(
        machines, output_dir=out_dir, model_register_dir=reg_dir
    ).build()
    assert len(first) == 4
    for _, mo in first:
        assert not _cache_marker(mo)
        assert os.path.exists(os.path.join(out_dir, mo.name, "model.pkl"))
        assert os.path.exists(os.path.join(out_dir, mo.name, "metadata.json"))
        # checkpointed metadata carries the apportioned durations, not the
        # provisional zeros written at assembly time
        from gordo_tpu import serializer

        meta = serializer.load_metadata(os.path.join(out_dir, mo.name))
        assert (
            meta["metadata"]["build_metadata"]["model"]
            ["model_training_duration_sec"] > 0.0
        )

    second = BatchedModelBuilder(
        _machines(config), output_dir=out_dir, model_register_dir=reg_dir
    ).build()
    assert all(_cache_marker(mo) for _, mo in second)

    # wipe one machine's entry: only it retrains
    victim = machines[2]
    disk_registry.delete_value(reg_dir, ModelBuilder(victim).cache_key)
    shutil.rmtree(os.path.join(out_dir, victim.name))
    third = BatchedModelBuilder(
        _machines(config), output_dir=out_dir, model_register_dir=reg_dir
    ).build()
    markers = {mo.name: _cache_marker(mo) for _, mo in third}
    assert markers == {
        "ckpt-0": True, "ckpt-1": True, "ckpt-2": False, "ckpt-3": True,
    }
    assert os.path.exists(os.path.join(out_dir, victim.name, "model.pkl"))


def test_fleet_replace_cache_retrains(tmp_path):
    config = "machines:" + _machine_block("rc-0")
    out_dir, reg_dir = str(tmp_path / "out"), str(tmp_path / "reg")
    kwargs = dict(output_dir=out_dir, model_register_dir=reg_dir)
    BatchedModelBuilder(_machines(config), **kwargs).build()
    again = BatchedModelBuilder(
        _machines(config), replace_cache=True, **kwargs
    ).build()
    assert not _cache_marker(again[0][1])


def test_profile_dir_captures_device_trace(tmp_path, monkeypatch):
    """GORDO_TPU_PROFILE_DIR wraps the fleet build in jax.profiler.trace
    and leaves an openable trace on disk (SURVEY §5 tracing hookup)."""
    import os

    monkeypatch.setenv("GORDO_TPU_PROFILE_DIR", str(tmp_path))
    config = "machines:" + _machine_block("prof-0")
    BatchedModelBuilder(_machines(config)).build()
    trace_root = tmp_path / "batched-build"
    assert trace_root.exists()
    files = [
        os.path.join(r, f)
        for r, _, fs in os.walk(trace_root)
        for f in fs
    ]
    assert files, "profiler produced no trace files"


# --------------------------------------------------- seeded-KFold KFCV plans
def _kfold_kfcv_block(name, n_splits=5, window=12):
    block = _kfcv_block(name, window=window)
    return block + f"""    evaluation:
      cv:
        sklearn.model_selection.KFold:
          n_splits: {n_splits}
          shuffle: true
          random_state: 0
"""


def test_kfold_kfcv_machines_take_batched_path():
    from gordo_tpu.parallel.batch_trainer import _plan_machine

    machines = _machines("machines:" + _kfold_kfcv_block("kfold-0"))
    plan = _plan_machine(machines[0])
    assert plan is not None and plan.kfcv
    assert plan.cv == ("kfold", 5, True, 0)


def test_kfold_cv_stays_serial_outside_kfcv():
    """Shuffled folds break the plain detector's rolling-threshold math and
    unseeded shuffles are irreproducible — both stay on the serial path."""
    from gordo_tpu.parallel.batch_trainer import _plan_machine

    plain = _machines("machines:" + _machine_block("plain-kf"))
    plain[0].evaluation["cv"] = {
        "sklearn.model_selection.KFold": {
            "n_splits": 5, "shuffle": True, "random_state": 0,
        }
    }
    assert _plan_machine(plain[0]) is None

    unseeded = _machines("machines:" + _kfcv_block("unseeded-kf"))
    unseeded[0].evaluation["cv"] = {
        "sklearn.model_selection.KFold": {"n_splits": 5, "shuffle": True}
    }
    assert _plan_machine(unseeded[0]) is None


def test_kfold_kfcv_threshold_math_matches_serial():
    """With seeded-KFold geometry (uneven fold sizes ⇒ padded test slices),
    _set_kfcv_thresholds must reproduce the serial KFCV detector's
    percentile thresholds exactly, given the same fold predictions."""
    from types import SimpleNamespace

    from sklearn.linear_model import LinearRegression
    from sklearn.model_selection import KFold
    from sklearn.preprocessing import MinMaxScaler

    from gordo_tpu.models.anomaly.diff import DiffBasedKFCVAnomalyDetector

    rng = np.random.RandomState(11)
    n_rows = 302  # 302 % 5 != 0: folds of 61/61/60/60/60 exercise padding
    X = rng.rand(n_rows, 4)
    y = X @ rng.rand(4, 4) + 0.01 * rng.rand(n_rows, 4)
    cv = KFold(n_splits=5, shuffle=True, random_state=0)

    serial = DiffBasedKFCVAnomalyDetector(
        base_estimator=LinearRegression(),
        scaler=MinMaxScaler(),
        window=24,
        shuffle=False,
    )
    serial.cross_validate(X=pd.DataFrame(X), y=pd.DataFrame(y), cv=cv)

    folds = [(tr, te) for tr, te in cv.split(X)]
    te_max = max(len(te) for _, te in folds)
    fold_bounds = [(len(tr), n_rows - te_max, n_rows) for tr, _ in folds]
    fold_preds = []
    for tr, te in folds:
        lr = LinearRegression().fit(X[tr], y[tr])
        pred = lr.predict(X[te])
        pad = te_max - len(te)
        if pad:
            # the program's padded test tail starts with train rows whose
            # predictions the assembly must discard
            pred = np.vstack([np.full((pad, y.shape[1]), 1e6), pred])
        fold_preds.append(pred)

    batched = DiffBasedKFCVAnomalyDetector(
        base_estimator=LinearRegression(),
        scaler=MinMaxScaler(),
        window=24,
        shuffle=False,
    )
    BatchedModelBuilder._set_kfcv_thresholds(
        None, batched, SimpleNamespace(y=y), fold_preds, fold_bounds, folds
    )
    np.testing.assert_allclose(
        batched.aggregate_threshold_, serial.aggregate_threshold_, rtol=1e-9
    )
    np.testing.assert_allclose(
        np.asarray(batched.feature_thresholds_),
        np.asarray(serial.feature_thresholds_),
        rtol=1e-9,
    )


def test_kfold_kfcv_batched_build_matches_serial_builder():
    """End to end: a seeded-KFold KFCV machine built batched vs the serial
    ModelBuilder. Fold geometry (splits metadata) must match EXACTLY; the
    thresholds come from independently-initialized trainings, so they match
    statistically (same order of magnitude), not bit-for-bit."""
    from gordo_tpu.builder.build_model import ModelBuilder
    from gordo_tpu.models.anomaly.diff import DiffBasedKFCVAnomalyDetector

    cfg = "machines:" + _kfold_kfcv_block("kfold-e2e")
    [(batched_model, batched_out)] = BatchedModelBuilder(
        _machines(cfg), serial_fallback=False
    ).build()
    serial_model, serial_out = ModelBuilder(_machines(cfg)[0]).build()
    assert isinstance(batched_model, DiffBasedKFCVAnomalyDetector)

    b_splits = batched_out.metadata.build_metadata.model.cross_validation.splits
    s_splits = serial_out.metadata.build_metadata.model.cross_validation.splits
    assert set(b_splits) == set(s_splits)
    for key in s_splits:
        assert str(b_splits[key]) == str(s_splits[key]), key

    ratio = batched_model.aggregate_threshold_ / serial_model.aggregate_threshold_
    assert 1 / 3 < ratio < 3, ratio
    feat_ratio = np.asarray(batched_model.feature_thresholds_) / np.asarray(
        serial_model.feature_thresholds_
    )
    assert np.all((feat_ratio > 1 / 3) & (feat_ratio < 3)), feat_ratio


def test_plain_detector_kfold_builds_via_serial_path():
    """A non-KFCV detector with a KFold cv config is rejected by the planner
    (rolling thresholds need contiguous folds) but must still BUILD through
    the serial ModelBuilder — capability is never lost, only speed."""
    from gordo_tpu.models.anomaly.diff import DiffBasedAnomalyDetector

    machines = _machines("machines:" + _machine_block("plain-kf-build"))
    machines[0].evaluation["cv"] = {
        "sklearn.model_selection.KFold": {
            "n_splits": 3, "shuffle": True, "random_state": 0,
        }
    }
    [(model, machine_out)] = BatchedModelBuilder(machines).build()
    assert isinstance(model, DiffBasedAnomalyDetector)
    assert np.isfinite(model.aggregate_threshold_)
    splits = machine_out.metadata.build_metadata.model.cross_validation.splits
    assert splits["fold-1-n-test"] > 0
    scores = machine_out.metadata.build_metadata.model.cross_validation.scores
    assert any("r2" in key for key in scores)
