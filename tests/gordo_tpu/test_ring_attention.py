"""
Ring attention (sequence parallelism) on the 8-virtual-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from gordo_tpu.ops.attention import dot_product_attention_xla
from gordo_tpu.parallel.ring_attention import (
    make_ring_attention,
    sequence_sharding,
)


def _seq_mesh(n: int) -> Mesh:
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_devices", [2, 8])
def test_ring_attention_matches_full_attention(causal, n_devices):
    mesh = _seq_mesh(n_devices)
    rng = np.random.RandomState(0)
    bh, t, dh = 4, 64, 8
    q, k, v = (
        jnp.asarray(rng.randn(bh, t, dh).astype(np.float32)) for _ in range(3)
    )
    ref = dot_product_attention_xla(q, k, v, causal=causal)

    ring = make_ring_attention(mesh, causal=causal)
    sharding = sequence_sharding(mesh)
    out = ring(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_output_stays_sequence_sharded():
    mesh = _seq_mesh(8)
    sharding = sequence_sharding(mesh)
    rng = np.random.RandomState(1)
    x = jax.device_put(
        jnp.asarray(rng.randn(2, 32, 8).astype(np.float32)), sharding
    )
    out = make_ring_attention(mesh)(x, x, x)
    assert out.sharding.is_equivalent_to(sharding, out.ndim)


def test_ring_attention_is_differentiable():
    mesh = _seq_mesh(4)
    sharding = sequence_sharding(mesh)
    rng = np.random.RandomState(2)
    q, k, v = (
        jax.device_put(
            jnp.asarray(rng.randn(1, 32, 8).astype(np.float32)), sharding
        )
        for _ in range(3)
    )
    ring = make_ring_attention(mesh, causal=True)

    g_ring = jax.grad(lambda q, k, v: jnp.sum(ring(q, k, v) ** 2), (0, 1, 2))(
        q, k, v
    )
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(
            dot_product_attention_xla(q, k, v, causal=True) ** 2
        ),
        (0, 1, 2),
    )(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
