"""
Test harness configuration.

XLA-CPU is the "fake backend" for TPU (SURVEY.md §4 takeaway): tests force the
CPU platform with 8 virtual devices so mesh/sharding logic runs anywhere; the
same code path runs unchanged on real TPU chips.
"""


import pytest

from gordo_tpu import serializer
from gordo_tpu.builder.local_build import local_build
from gordo_tpu.dataset import SensorTag


@pytest.fixture(scope="session")
def sensors():
    return [SensorTag(f"tag-{i}", asset="asset") for i in range(4)]


@pytest.fixture(scope="session")
def gordo_name():
    return "machine-1"


@pytest.fixture(scope="session")
def second_gordo_name():
    return "machine-2"


@pytest.fixture(scope="session")
def gordo_project():
    return "gordo-test"


@pytest.fixture(scope="session")
def config_str(gordo_name: str, second_gordo_name: str, sensors):
    tag_lines = "\n".join(f"        - {t.name}" for t in sensors)
    return f"""
machines:
  - name: {gordo_name}
    dataset:
      tags:
{tag_lines}
      target_tag_list:
{tag_lines}
      train_start_date: '2019-01-01T00:00:00+00:00'
      train_end_date: '2019-01-10T00:00:00+00:00'
      asset: asgb
      data_provider:
        type: RandomDataProvider
    metadata:
      information: Some sweet information about the model
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        require_thresholds: false
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
            - sklearn.preprocessing.MinMaxScaler
            - gordo_tpu.models.models.AutoEncoder:
                kind: feedforward_hourglass
                epochs: 1
  - name: {second_gordo_name}
    dataset:
      tags:
{tag_lines}
      target_tag_list:
{tag_lines}
      train_start_date: '2019-01-01T00:00:00+00:00'
      train_end_date: '2019-01-10T00:00:00+00:00'
      asset: asgb
      data_provider:
        type: RandomDataProvider
    metadata:
      information: Some sweet information about the model
    model:
      gordo_tpu.models.anomaly.diff.DiffBasedAnomalyDetector:
        window: 144
        require_thresholds: false
        base_estimator:
          sklearn.pipeline.Pipeline:
            steps:
            - sklearn.preprocessing.MinMaxScaler
            - gordo_tpu.models.models.AutoEncoder:
                kind: feedforward_hourglass
                epochs: 1
"""


@pytest.fixture(scope="session")
def gordo_revision():
    return "1604321820000"


@pytest.fixture(scope="session")
def model_collection_directory(tmp_path_factory, gordo_revision: str):
    path = tmp_path_factory.mktemp("collection") / gordo_revision
    path.mkdir(parents=True, exist_ok=True)
    return str(path)


@pytest.fixture(scope="session")
def trained_model_directories(model_collection_directory: str, config_str: str):
    """Train real models once per session (reference conftest.py:225-244)."""
    import os as _os

    model_directories = {}
    for model, machine in local_build(config_str=config_str):
        metadata_dict = machine.to_dict()
        model_name = metadata_dict["name"]
        model_dir = _os.path.join(model_collection_directory, model_name)
        _os.makedirs(model_dir, exist_ok=True)
        serializer.dump(model, model_dir, metadata=metadata_dict)
        model_directories[model_name] = model_dir
    return model_directories


@pytest.fixture(scope="session")
def trained_model_directory(trained_model_directories, gordo_name):
    return trained_model_directories[gordo_name]


@pytest.fixture
def metadata(trained_model_directory):
    return serializer.load_metadata(trained_model_directory)


@pytest.fixture(scope="session")
def X_payload(sensors):
    """The canonical server-test input frame (20 rows x the session's
    sensor tags) — shared by the in-process server tests and the
    multi-process pool drive so the two suites pin one wire payload."""
    import numpy as np
    import pandas as pd

    idx = pd.date_range("2020-01-01", periods=20, freq="10min", tz="UTC")
    return pd.DataFrame(
        np.random.RandomState(0).rand(20, len(sensors)),
        columns=[t.name for t in sensors],
        index=idx,
    )
