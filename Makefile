# gordo-tpu build/test targets (reference parity: Makefile:1-40, collapsed
# to the one image the TPU workflow actually uses)

IMG_NAME ?= gordo-tpu
DOCKER_REGISTRY ?= ghcr.io/gordo-tpu
VERSION ?= $(shell python -c "import gordo_tpu; print(gordo_tpu.__version__)" 2>/dev/null || echo dev)

# the single image every workflow pod runs (template {{ image }})
image:
	docker build . -f Dockerfile -t $(IMG_NAME):$(VERSION)

push: image
	docker tag $(IMG_NAME):$(VERSION) $(DOCKER_REGISTRY)/$(IMG_NAME):$(VERSION)
	docker push $(DOCKER_REGISTRY)/$(IMG_NAME):$(VERSION)

# full suite on the 8-virtual-device CPU mesh (how CI runs; conftest.py
# forces JAX_PLATFORMS=cpu + xla_force_host_platform_device_count=8)
test:
	python -m pytest tests/ -q

# multichip sharding compile check (same entry the driver uses)
dryrun:
	python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# render the example config through the real CLI and schema-validate the
# resulting Workflow docs — the no-cluster equivalent of `argo lint`
smoke:
	python -m gordo_tpu.cli workflow generate \
		--machine-config examples/config.yaml --project-name smoke-test \
		--client-start-date 2019-01-01T00:00:00Z \
		--client-end-date 2019-01-02T00:00:00Z \
		| python -m gordo_tpu.cli workflow validate -

# every Jinja branch of the workflow template rendered + linted; run after
# ANY edit under gordo_tpu/workflow/resources/ (round-4 postmortem: a
# template edit shipped unrendered and killed `workflow generate`)
render-gate:
	python -m pytest tests/gordo_tpu/test_workflow_template_render.py -q

bench:
	python bench.py

# serving hot path only (ISSUE 19): the smoke + open-loop load sections —
# fast-lane/UDS/gateway percentiles, syscalls per request, pipeline
# overlaps — without the training-side sections. Minutes, not the full
# harness; the partial record must NOT be committed as a BENCH_r*.json
# round (bench-gate compares full rounds).
bench-hotpath:
	GORDO_TPU_BENCH_SECTIONS=tpu_smoke,serving_load python bench.py

# hard perf regression gate: diff the two most recent BENCH_r*.json
# records with comparable-section matching (exit 1 on a >15% regression;
# see docs/benchmarking.md "Reading the gate")
bench-gate:
	python scripts/bench_compare.py --latest .

# schema check on every checked-in bench record (also runs in tier-1)
lint-bench-records:
	python scripts/lint_bench_record.py

# metric <-> dashboard consistency, both directions: every catalog metric
# is plotted/documented somewhere, and every gordo_* name a dashboard
# panel queries exists in a metrics catalog (also runs in tier-1)
lint-dashboards:
	python scripts/lint_metric_names.py

# vocabulary + structure check on every committed chaos scenario
lint-chaos-scenarios:
	python scripts/lint_chaos_scenario.py

# one real chaos drill against a live 3-node stack: kill a node mid-ramp,
# assert the availability floor, failover bound, exact histogram merge and
# that the failover is visible as a hedge-arm span in one stitched trace
# (see docs/robustness.md "Chaos conductor"); tier-1 runs the same drill
# (scaled down) plus these invariants via tests/gordo_tpu/test_chaos_conductor.py
chaos-smoke:
	JAX_PLATFORMS=cpu python -m gordo_tpu.cli.cli chaos run \
		resources/chaos/kill_node_mid_ramp.yaml

# burst-profile a live event-loop server through its own debug surface
# and assert the capture contains the event-loop frames (see
# docs/observability.md "Profiling a live server")
profile-smoke:
	JAX_PLATFORMS=cpu python scripts/profile_smoke.py

.PHONY: image push test dryrun smoke render-gate bench bench-hotpath \
	bench-gate lint-bench-records lint-dashboards lint-chaos-scenarios \
	chaos-smoke profile-smoke
